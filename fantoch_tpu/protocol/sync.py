"""Rejoin catch-up: committed-command sync for restarted replicas.

A replica that crashes and restarts from its WAL + snapshot (run/wal.py,
sim crash-restart) knows everything it committed before the crash but
nothing the mesh decided while it was down.  Peers dropped its frames the
moment they declared it dead, so the network never replays that history —
the returning replica must *pull* it.  This mixin is the pull:

1. **MSync** — on :meth:`rejoin` the restarted process broadcasts its
   committed-dot horizon: the GC tracker's own AEClock (contiguous
   frontier + above-exceptions), which survives in the snapshot and —
   because GC only trims ``_cmds``, never the clock — also covers commits
   whose info was already garbage-collected locally.
2. **MSyncReply** — each live peer scans its commit-info store for
   committed dots outside that horizon and streams protocol-specific
   commit records back, chunked (:data:`SYNC_CHUNK` per message) so one
   reply never balloons.  Retention is guaranteed by the
   executed-everywhere GC clock: while the requester was down its
   executed frontier froze, so the mesh's stability meet — and therefore
   GC — stalled at its last notification; everything it missed is still
   in some live peer's ``_cmds``.
3. **Apply** — the requester applies each record through the protocol's
   normal commit machinery (payload adoption + MCommit handler), which is
   idempotent per dot (``Status.COMMIT`` short-circuit), so the same
   record arriving from several peers — or racing a recovery-decided
   commit — is exactly-once.

Protocols plug in two hooks (:meth:`SyncMixin._sync_record` /
:meth:`SyncMixin._apply_sync_record`) plus an optional
:meth:`SyncMixin._sync_backfill_actions` used by Newt: vote-frontier gaps
cannot be reconstructed from commit records alone, but every process's
issued votes on a key are exactly the contiguous range ``[1, its key
clock]``, so peers (and the rejoiner) re-state that range wholesale as
detached votes — ranges dedup in the vote tables, and the restarted
replica's stability frontier heals instead of stalling below a
permanent gap.  Caesar plugs in the same two hooks with records carrying
the decided ``(clock, preds)`` pair; it needs no backfill (the
predecessor index rebuilds entirely from applied records).

Leader-based FPaxos orders a single slot log rather than per-process dot
clocks, so it rejoins through the sibling :class:`SlotSyncMixin` below:
``MSlotSync`` carries the rejoiner's contiguous committed-slot floor and
peers stream ``(slot, command)`` records from their retained chosen log
(pruned only at global stability, which stalled while the replica was
down).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Tuple

from fantoch_tpu.core.ids import ProcessId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.protocol.base import ToSend

# commit records per MSyncReply message: bounds per-message work at the
# requester and keeps the sim's per-delivery cost flat
SYNC_CHUNK = 128


@dataclass
class MSync:
    """Restarted replica -> everyone: my committed horizon (an
    ``AEClock[ProcessId]``); send me what I missed."""

    committed: Any


@dataclass
class MSyncReply:
    """One chunk of protocol-specific commit records past the
    requester's horizon."""

    records: List[Tuple]


@dataclass
class MSyncBackfill:
    """A peer's frontier backfill (Newt: its vote column ``[1, key
    clock]`` per key, minus pending-held ranges), gated on ``records``:
    the receiver applies it only after it has applied that many of the
    peer's sync records.  The old scheme shipped the backfill as a plain
    detached-votes message appended after the record chunks and relied
    on in-order delivery — but fault-plan links (delay jitter, reorder,
    retransmits; the run layer's reconnect windows are the analog) can
    deliver the backfill FIRST, and a consumed range released before its
    dot's ops arrive lets timestamp stability overtake the commit: the
    rejoiner executes a higher-clock command around a lower-clock one
    and diverges from the live history (fuzzer-found, soak seed 99)."""

    votes: Any
    records: int


@dataclass
class MSlotSync:
    """Restarted replica -> everyone (slot-ordered protocols): my
    contiguous committed-slot floor; stream me the chosen slots above
    it."""

    floor: int


@dataclass
class MSlotSyncReply:
    """One chunk of ``(slot, command)`` chosen records past the
    requester's floor."""

    records: List[Tuple]


class SlotSyncMixin:
    """Slot-log rejoin catch-up for leader-based protocols (FPaxos): the
    dot-horizon MSync above keys off per-process AEClocks, but a slot
    protocol's history is one shared log — the rejoiner sends its
    executed/committed slot floor and live peers stream the chosen
    ``(slot, command)`` records above it from their retained chosen log
    (retention is the same executed-everywhere argument: the dead
    replica's GC watermark froze, so global stability — and therefore
    chosen-log pruning — stalled at its last report).  Application runs
    through the protocol's normal chosen handler, which is idempotent per
    slot (chosen-slot dedup + the ``SlotGCTrack.stable_floor`` straggler
    guard), so overlapping peer replies are exactly-once.

    Requires from the host: ``self.bp``, ``self._to_processes``, a
    ``_slot_sync_floor()`` (the rejoiner's contiguous committed-slot
    frontier), ``_slot_sync_records(floor)`` (sorted chosen records above
    the floor this peer can serve), and ``_apply_slot_sync_record``."""

    def _slot_sync_enabled(self) -> bool:
        # retention needs the GC watermark plane; without it the chosen
        # log is pruned by the bounded dedup window instead and cannot
        # promise coverage
        return (
            self.bp.config.gc_interval_ms is not None
            and self.bp.config.shard_count == 1
        )

    def rejoin(self, time: SysTime) -> None:
        if not self._slot_sync_enabled():
            return
        targets = self.bp.all_but_me()
        if not targets:
            return
        self._to_processes.append(
            ToSend(targets, MSlotSync(self._slot_sync_floor()))
        )

    def handle_slot_sync_message(self, from_: ProcessId, msg: Any, time: SysTime) -> bool:
        """Dispatch a slot-sync message; returns False if ``msg`` is not
        one."""
        if isinstance(msg, MSlotSync):
            if self._slot_sync_enabled():
                records = self._slot_sync_records(msg.floor)
                for start in range(0, len(records), SYNC_CHUNK):
                    self._to_processes.append(
                        ToSend({from_}, MSlotSyncReply(records[start : start + SYNC_CHUNK]))
                    )
        elif isinstance(msg, MSlotSyncReply):
            for record in msg.records:
                self._apply_slot_sync_record(from_, record, time)
        else:
            return False
        return True

    # --- hooks for the host protocol ---

    def _slot_sync_floor(self) -> int:
        raise NotImplementedError

    def _slot_sync_records(self, floor: int):
        raise NotImplementedError

    def _apply_slot_sync_record(self, from_: ProcessId, record, time: SysTime) -> None:
        raise NotImplementedError


class SyncMixin:
    """Requires from the host protocol: ``self.bp`` (BaseProcess),
    ``self._cmds`` (CommandsInfo with ``items()``), ``self._gc_track``
    (GCTrack), ``self._to_processes`` (deque), and a ``Status`` whose
    committed state is ``"commit"``.  Single-shard only, like the
    recovery plane (cross-shard commit aggregation state dies with the
    dot owner)."""

    _SYNC_STATUS_COMMIT = "commit"

    def _sync_enabled(self) -> bool:
        return self.bp.config.shard_count == 1

    # --- the restarted side ---

    def rejoin(self, time: SysTime) -> None:
        if not self._sync_enabled():
            return
        # fresh catch-up round: per-peer record counters and held
        # backfills from a previous life must not leak into this round's
        # barrier (a restored counter would release a new backfill early)
        self._sync_records_seen = {}
        self._held_backfills = {}
        targets = self.bp.all_but_me()
        if not targets:
            return
        self._to_processes.append(
            ToSend(targets, MSync(self._gc_track.my_clock()))
        )
        # the requester's own backfill toward the live peers needs no
        # barrier: peers hold every commit its consumed ranges belong to
        # (in-flight commits at crash time fanned out to them, and its
        # pending dots are subtracted)
        self._sync_backfill_actions(targets)

    # --- wire handlers ---

    def handle_sync_message(self, from_: ProcessId, msg: Any, time: SysTime) -> bool:
        """Dispatch a sync message; returns False if ``msg`` is not one."""
        if isinstance(msg, MSync):
            self._handle_msync(from_, msg.committed, time)
        elif isinstance(msg, MSyncReply):
            # count DISTINCT records toward the backfill barrier: a
            # duplicated/retransmitted chunk must not inflate the counter
            # past the threshold while another chunk is still in flight
            # (that would release the backfill early — the very hazard
            # the barrier exists for)
            seen = self._sync_seen().setdefault(from_, set())
            for record in msg.records:
                seen.add(record[0])
                self._apply_sync_record(from_, record, time)
            self._maybe_apply_backfill(from_, time)
        elif isinstance(msg, MSyncBackfill):
            # barrier (see MSyncBackfill): hold until every record this
            # peer streamed has been applied here — delivery can reorder
            # the backfill ahead of its own record chunks, and a consumed
            # range released before its dot's ops arrive lets stability
            # overtake the commit at the rejoiner
            self._held()[from_] = (msg.votes, msg.records)
            self._maybe_apply_backfill(from_, time)
        else:
            return False
        return True

    def _sync_seen(self) -> dict:
        if not hasattr(self, "_sync_records_seen"):
            self._sync_records_seen = {}
        return self._sync_records_seen

    def _held(self) -> dict:
        if not hasattr(self, "_held_backfills"):
            self._held_backfills = {}
        return self._held_backfills

    def _maybe_apply_backfill(self, from_: ProcessId, time: SysTime) -> None:
        held = self._held().get(from_)
        if held is None:
            return
        votes, needed = held
        if (
            len(self._sync_seen().get(from_, ())) >= needed
            and not self._sync_backfill_blocked()
        ):
            self._held().pop(from_, None)
            self._apply_sync_backfill(from_, votes, time)

    def _sync_release_backfills(self, time: SysTime) -> None:
        """Periodic retry hook: re-check every held backfill (the
        buffered-commit gate clears as in-flight commits resolve, with
        no message to anchor the release on)."""
        for from_ in list(self._held()):
            self._maybe_apply_backfill(from_, time)

    def _handle_msync(self, from_: ProcessId, committed, time: SysTime) -> None:
        if not self._sync_enabled():
            return
        records = []
        # sorted: chunk contents are a pure function of protocol state,
        # not dict insertion history — same-seed traces stay identical
        for dot, info in sorted(self._cmds.items()):
            if info.status != self._SYNC_STATUS_COMMIT:
                continue
            if committed.contains(dot.source, dot.sequence):
                continue
            records.append(self._sync_record(dot, info))
        for start in range(0, len(records), SYNC_CHUNK):
            self._to_processes.append(
                ToSend({from_}, MSyncReply(records[start : start + SYNC_CHUNK]))
            )
        # even with no missing commits the requester may have vote gaps —
        # but the backfill may only APPLY after the records above (the
        # MSyncBackfill barrier), because nothing guarantees in-order
        # delivery under fault plans
        payload = self._sync_backfill_payload()
        if payload is not None:
            self._to_processes.append(
                ToSend({from_}, MSyncBackfill(payload, len(records)))
            )

    # --- hooks for the host protocol ---

    def _sync_backfill_payload(self):
        """Optional: the frontier-backfill payload a record-serving peer
        sends barrier-gated (Newt's detached-vote re-statement).  Default
        None — no backfill message."""
        return None

    def _sync_backfill_blocked(self) -> bool:
        """Receiver-side gate shared by BOTH backfill directions: a
        backfill must not apply while this process holds payload-less
        BUFFERED commits — the backfilled column can cover ranges the
        sender consumed for exactly those commits, and releasing them
        before the ops land lets stability overtake the commit (the
        fuzzer-found live-peer variant: a rejoiner's backfill reached a
        peer whose copy of an in-flight commit was still lost behind
        retransmits).  Default False; Newt checks its buffered-MCommit
        map."""
        return False

    def _apply_sync_backfill(self, from_: ProcessId, votes, time: SysTime) -> None:
        """Apply a peer's barrier-released backfill.  Default no-op."""

    def _sync_backfill_actions(self, targets) -> None:
        """Optional: queue frontier-backfill actions toward ``targets``
        (Newt's detached-vote re-statement on the REJOINER side, where
        no barrier is needed).  Default no-op."""

    def _sync_record(self, dot, info):
        """One commit record for ``dot`` (committed here, unknown to the
        requester)."""
        raise NotImplementedError

    def _apply_sync_record(self, from_: ProcessId, record, time: SysTime) -> None:
        """Apply one peer commit record; must be idempotent per dot."""
        raise NotImplementedError
