"""Shared machinery for dependency-graph consensus protocols (EPaxos, Atlas).

The reference implements EPaxos (fantoch_ps/src/protocol/epaxos.rs) and
Atlas (fantoch_ps/src/protocol/atlas.rs) as two nearly-identical ~1000-line
files; here the shared collect/commit/consensus/GC skeleton lives once and
the protocols specialize three points:
- quorum sizes (EPaxos: minority-tolerating fixed f; Atlas: n//2 + f),
- the fast-path condition over reported deps (union equality vs threshold
  union),
- whether the coordinator acks itself (EPaxos skips self-acks and sizes the
  quorum-deps tracker at fast_quorum_size - 1; Atlas counts itself).
"""

from __future__ import annotations

import functools

from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.graph.executor import (
    GraphAdd,
    GraphAddBatch,
    GraphExecutor,
    GraphNoop,
)
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.commit_gc import (
    CommitGCMixin,
    GarbageCollectionEvent,
    MCommitDot,
)
from fantoch_tpu.protocol.common.graph_deps import Dependency, KeyDeps, QuorumDeps
from fantoch_tpu.protocol.common.synod import (
    MAccept,
    MAccepted as SynodMAccepted,
    MChosen,
    Synod,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo
from fantoch_tpu.protocol.recovery import (
    MRecoveryPrepare,
    MRecoveryPromise,
    RecoveryEvent,
    RecoveryMixin,
)
from fantoch_tpu.protocol.sync import (
    MSync,
    MSyncBackfill,
    MSyncReply,
    SyncMixin,
)
from fantoch_tpu.protocol.partial import (
    MForwardSubmit,
    MShardAggregatedCommit,
    MShardCommit,
    PartialCommitMixin,
)
from fantoch_tpu.run.routing import (
    GC_WORKER_INDEX,
    worker_dot_index_shift,
    worker_index_no_shift,
)


# --- messages (epaxos.rs:675-702 / atlas.rs:836-871) ---


class _CommitBuffer:
    """Array columns of committed commands, appended at commit time and
    flushed as one GraphAddBatch per executor drain."""

    __slots__ = ("shard_id", "src", "seq", "key", "deps", "cmds", "count")

    def __init__(self, shard_id: ShardId):
        self.shard_id = shard_id
        self.src: list = []
        self.seq: list = []
        self.key: list = []
        self.deps: list = []  # per-command tuple of packed dep dots
        self.cmds: list = []
        self.count = 0

    def append(self, dot: Dot, cmd: Command, deps) -> None:
        from fantoch_tpu.executor.graph.batched import key_hash

        self.src.append(dot.source)
        self.seq.append(dot.sequence)
        if cmd.key_count(self.shard_id) == 1:
            self.key.append(key_hash(next(iter(cmd.keys(self.shard_id)))))
        else:
            self.key.append(-1)
        self.deps.append(
            tuple(
                (d.dot.source << 32) | d.dot.sequence for d in deps if d.dot != dot
            )
        )
        self.cmds.append(cmd)
        self.count += 1

    def flush(self) -> GraphAddBatch:
        import numpy as np

        width = max((len(d) for d in self.deps), default=1) or 1
        dep_dots = np.full((self.count, width), -1, dtype=np.int64)
        for i, d in enumerate(self.deps):
            dep_dots[i, : len(d)] = d
        out = GraphAddBatch(
            np.array(self.src, dtype=np.int64),
            np.array(self.seq, dtype=np.int64),
            np.array(self.key, dtype=np.int32),
            dep_dots,
            self.cmds,
        )
        self.src, self.seq, self.key, self.deps, self.cmds = [], [], [], [], []
        self.count = 0
        return out


@dataclass
class MCollect:
    dot: Dot
    cmd: Command
    deps: Set[Dependency]
    quorum: Set[ProcessId]


@dataclass
class MCollectAck:
    dot: Dot
    deps: Set[Dependency]


@dataclass
class ConsensusValue:
    """(is_noop, deps) — the value agreed on per dot (epaxos.rs:602-621).

    ``bottom()`` (the synod's pre-ack initial value) is the *noop*: a
    recovery promise carrying it means "this acceptor never acked the
    MCollect", which is exactly what distinguishes a never-payloaded dot
    (recovered as a committed noop) from a real report with empty deps.
    """

    deps: Set[Dependency]
    is_noop: bool = False

    @staticmethod
    def bottom() -> "ConsensusValue":
        return ConsensusValue(set(), is_noop=True)


@dataclass
class MCommit:
    dot: Dot
    value: ConsensusValue
    # payload piggyback on recovery chosen-replies: a rejoined replica can
    # hold a buffered commit for a dot whose MCollect it missed while
    # down AND that was still in flight when the MSync records were cut —
    # without the payload here, the prepare/chosen exchange would loop
    # payload-less forever and the dot's (subtracted-from-backfill) votes
    # would never fold (fuzzer-found rejoin stall)
    cmd: Optional[Command] = None


@dataclass
class MConsensus:
    dot: Dot
    ballot: int
    value: ConsensusValue
    # payload piggyback on recovery rounds, so a recovered value can commit
    # at processes the original MCollect broadcast never reached
    cmd: Optional[Command] = None


@dataclass
class MConsensusAck:
    dot: Dot
    ballot: int


class Status:
    START = "start"
    PAYLOAD = "payload"
    COLLECT = "collect"
    COMMIT = "commit"


def _recovery_proposal_gen(values):
    """Recovery value selection over the ballot-0 reports of an n-f promise
    quorum (protocol/recovery.py; the reference's todo!() at
    epaxos.rs:627-629).  Reports are the deps fast-quorum members set when
    acking the MCollect plus non-quorum holders' "late reports" (staged at
    payload receipt so conflict edges survive losing the
    quorum-intersection member); bottom (``is_noop``) marks acceptors that
    never saw the payload.  No report anywhere -> the dot is recovered as
    a committed noop; otherwise the union of reports — a free (therefore
    safe) choice whenever no commit was decided before recovery began,
    which protocol/recovery.py's safety note reduces to the
    recovery_delay_ms-exceeds-delivery-delay assumption."""
    deps: Set[Dependency] = set()
    reported = False
    for value in values.values():
        if not value.is_noop:
            reported = True
            deps |= value.deps
    if not reported:
        return ConsensusValue(set(), is_noop=True)
    return ConsensusValue(deps)


def _graph_info_factory(pid, _sid, _cfg, _fq, _wq, *, n, f, quorum_deps_size):
    """Picklable per-dot info factory (the model checker pickles state);
    a partial over primitives pickles by reference + args."""
    return GraphCommandInfo(pid, n, f, quorum_deps_size)


class GraphCommandInfo:
    """Per-dot lifecycle info (epaxos.rs:628-668)."""

    __slots__ = ("status", "quorum", "synod", "cmd", "quorum_deps")

    def __init__(self, process_id: ProcessId, n: int, f: int, quorum_deps_size: int):
        self.status = Status.START
        self.quorum: Set[ProcessId] = set()
        self.synod: Synod[ConsensusValue] = Synod(
            process_id, n, f, _recovery_proposal_gen, ConsensusValue.bottom()
        )
        self.cmd: Optional[Command] = None
        self.quorum_deps = QuorumDeps(quorum_deps_size)


class GraphProtocol(PartialCommitMixin, RecoveryMixin, SyncMixin, CommitGCMixin, Protocol):
    """Common skeleton; see module docstring for the specialization points."""

    Executor = GraphExecutor

    # --- subclass hooks ---

    @classmethod
    def quorum_sizes(cls, config: Config) -> Tuple[int, int]:
        raise NotImplementedError

    @classmethod
    def consensus_f(cls, config: Config) -> int:
        """The f used by the embedded synod."""
        raise NotImplementedError

    @classmethod
    def coordinator_self_ack(cls) -> bool:
        """Whether the coordinator's own deps join the quorum-deps tracker."""
        raise NotImplementedError

    def fast_path_condition(self, info: GraphCommandInfo) -> Tuple[Set[Dependency], bool]:
        raise NotImplementedError

    # --- construction ---

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        fast_quorum_size, write_quorum_size = self.quorum_sizes(config)
        self.bp = BaseProcess(process_id, shard_id, config, fast_quorum_size, write_quorum_size)
        self.key_deps = KeyDeps(shard_id)
        f = self.consensus_f(config)
        quorum_deps_size = (
            fast_quorum_size if self.coordinator_self_ack() else fast_quorum_size - 1
        )
        self._cmds: CommandsInfo[GraphCommandInfo] = CommandsInfo(
            process_id,
            shard_id,
            config,
            fast_quorum_size,
            write_quorum_size,
            functools.partial(
                _graph_info_factory, n=config.n, f=f,
                quorum_deps_size=quorum_deps_size,
            ),
        )
        self._gc_track = GCTrack(process_id, shard_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[Any] = deque()
        # commit notifications that arrived before the MCollect (possible
        # even without failures, due to connection multiplexing)
        self._buffered_commits: Dict[Dot, Tuple[ProcessId, ConsensusValue]] = {}
        # single-shard commits cross the executor boundary as arrays built
        # incrementally here at commit time (GraphAddBatch — VERDICT r2
        # item 2); multi-shard keeps per-command GraphAdd because remote
        # Dependency shard sets must survive the crossing
        self._commit_buffer = (
            _CommitBuffer(shard_id) if config.shard_count == 1 else None
        )
        self._init_partial()
        self._init_recovery()

    def periodic_events(self):
        return list(self.gc_periodic_events()) + self.recovery_periodic_events()

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    @classmethod
    def supports_partial_replication(cls) -> bool:
        """EPaxos does not support partial replication (mirroring the
        reference: no partial messages in fantoch_ps/src/protocol/epaxos.rs);
        Atlas does (atlas.rs:157-165)."""
        return False

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        if cmd.shard_count > 1:
            assert self.supports_partial_replication(), (
                f"{type(self).__name__} does not support multi-shard commands"
            )
        dot = self._handle_submit(dot, cmd, target_shard=True)
        # trace: dot assigned + payload owned at the coordinator
        self.bp.trace_span("payload", cmd.rifl, dot=dot)

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MCollect):
            self._handle_mcollect(from_, msg.dot, msg.cmd, msg.quorum, msg.deps, time)
        elif isinstance(msg, MCollectAck):
            self._handle_mcollectack(from_, msg.dot, msg.deps)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(
                from_, msg.dot, msg.value, time, getattr(msg, "cmd", None)
            )
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg.dot, msg.ballot, msg.value, msg.cmd, time)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg.dot, msg.ballot)
        elif self.handle_recovery_message(from_, msg, time):
            pass
        elif self.handle_sync_message(from_, msg, time):
            pass
        elif self.handle_partial_message(from_, msg):
            pass
        elif not self.handle_gc_message(from_, msg):
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        if isinstance(event, RecoveryEvent):
            self.handle_recovery_event(time)
            return
        assert isinstance(event, GarbageCollectionEvent)
        self.handle_gc_event()

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        if self._commit_buffer is not None and self._commit_buffer.count:
            return self._commit_buffer.flush()
        return self._to_executors.popleft() if self._to_executors else None

    @classmethod
    def parallel(cls) -> bool:
        return KeyDeps.parallel()

    @classmethod
    def leaderless(cls) -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_submit(
        self, dot: Optional[Dot], cmd: Command, target_shard: bool
    ) -> Dot:
        dot = dot if dot is not None else self.bp.next_dot()
        # forward the submit to the other shards the command touches
        # (no-op for single-shard commands / forwarded submits)
        self.partial_submit_actions(dot, cmd, target_shard)
        deps = self.key_deps.add_cmd(dot, cmd, None)
        mcollect = MCollect(dot, cmd, deps, self.bp.fast_quorum())
        self._to_processes.append(ToSend(self.bp.all(), mcollect))
        return dot

    def _handle_mcollect(self, from_, dot, cmd, quorum, remote_deps, time) -> None:
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status != Status.START:
            return
        self._recovery_track(dot, time)
        if self.bp.process_id not in quorum:
            # not in the fast quorum: just store the payload; replay any
            # buffered commit now that we have it
            info.status = Status.PAYLOAD
            info.cmd = cmd
            if self._recovery_enabled():
                # record the payload in the conflict index and stage a
                # ballot-0 "late report": if this dot ever needs recovery,
                # our promise then carries the conflict edges we know
                # about.  Without it, two dots recovered from disjoint
                # survivor sets can commit with no dependency edge between
                # them — the quorum-intersection member that would have
                # reported the edge being exactly the crashed one
                deps = self.key_deps.add_cmd(dot, cmd, remote_deps)
                info.synod.set_if_not_accepted(lambda: ConsensusValue(set(deps)))
            self._replay_buffered_commit(dot, time)
            return

        message_from_self = from_ == self.bp.process_id
        if message_from_self:
            # coordinator already computed deps at submit
            deps = remote_deps
        else:
            deps = self.key_deps.add_cmd(dot, cmd, remote_deps)

        info.cmd = cmd
        value = ConsensusValue(set(deps))
        if not info.synod.set_if_not_accepted(lambda: value):
            # a recovery prepare already owns a higher ballot for this dot:
            # our promise forbids the ballot-0 ack, so keep the payload and
            # let recovery drive the commit
            info.status = Status.PAYLOAD
            self._replay_buffered_commit(dot, time)
            return
        info.status = Status.COLLECT
        info.quorum = set(quorum)

        if self.coordinator_self_ack() or not message_from_self:
            self._to_processes.append(ToSend({from_}, MCollectAck(dot, deps)))
        # with recovery in play a commit can be decided without this
        # member's ack and thus arrive before its MCollect — replay it
        self._replay_buffered_commit(dot, time)

    def _replay_buffered_commit(self, dot, time) -> None:
        buffered = self._buffered_commits.pop(dot, None)
        if buffered is not None:
            buf_from, buf_value = buffered
            self._handle_mcommit(buf_from, dot, buf_value, time)

    def _handle_mcollectack(self, from_, dot, deps) -> None:
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        if not self.coordinator_self_ack():
            assert from_ != self.bp.process_id
        info = self._cmds.get(dot)
        if info.status != Status.COLLECT:
            return
        if info.quorum_deps.contains(from_):
            # duplicate ack (at-least-once delivery): re-counting reports
            # would inflate the Atlas fast-path threshold unsoundly, and a
            # late duplicate after quorum completion (slow path / recovery
            # join keep status COLLECT) would trip the size assert
            return
        info.quorum_deps.add(from_, deps)
        if not info.quorum_deps.all():
            return
        final_deps, fast_path = self.fast_path_condition(info)
        value = ConsensusValue(final_deps)
        if not info.synod.can_skip_prepare():
            # a recovery proposer owns a higher ballot: neither the
            # unilateral fast-path commit nor the first-ballot shortcut is
            # sound anymore — join recovery with a full prepare instead
            prepare = info.synod.new_prepare()
            self._to_processes.append(
                ToSend(
                    self.bp.all(), MRecoveryPrepare(dot, prepare.ballot, info.cmd)
                )
            )
            return
        if fast_path:
            self.bp.fast_path(dot, info.cmd)
            self._mcommit_actions(dot, value)
        else:
            self.bp.slow_path(dot, info.cmd)
            ballot = info.synod.skip_prepare()
            self._to_processes.append(
                ToSend(self.bp.write_quorum(), MConsensus(dot, ballot, value))
            )

    def _handle_mcommit(self, from_, dot, value, time, cmd=None) -> None:
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status == Status.COMMIT:
            return
        if cmd is not None and info.cmd is None:
            # recovery chosen-reply piggyback: adopt so the commit below
            # can proceed instead of buffering payload-less.  A commit
            # buffered earlier for this dot is superseded by this one
            # (consensus decided the same value) — pop it or it leaks
            self._buffered_commits.pop(dot, None)
            info.cmd = cmd
            if info.status == Status.START:
                info.status = Status.PAYLOAD
        if value.is_noop:
            # recovered noop (the dot was never payloaded anywhere the
            # promise quorum could see): nothing executes — the executor's
            # noop seam just resolves any dependents waiting on the dot
            self._to_executors.append(GraphNoop(dot))
            self._commit_bookkeeping(info, from_, dot, value)
            return
        if info.status == Status.START:
            # MCollect may arrive after MCommit (multiplexing): buffer —
            # and track for recovery: if the MCollect never comes (it was
            # broadcast while this replica was down, and the commit was
            # still in flight when the rejoin records were cut), only the
            # recovery exchange can fetch the payload
            self._buffered_commits[dot] = (from_, value)
            self._recovery_track(dot, time)
            return
        cmd = info.cmd
        assert cmd is not None, "there should be a command payload"
        if self._commit_buffer is not None:
            self._commit_buffer.append(dot, cmd, value.deps)
        else:
            self._to_executors.append(GraphAdd(dot, cmd, set(value.deps)))
        self._commit_bookkeeping(info, from_, dot, value)

    def _commit_bookkeeping(self, info, from_, dot, value) -> None:
        info.status = Status.COMMIT
        if self.bp.audit_commits is not None:
            # audit plane: the agreed value is the dep set (noop commits
            # carry no command — record rifl None so the auditor never
            # counts them as a lost command)
            self.bp.audit_commit(
                dot,
                None if value.is_noop else (
                    info.cmd.rifl if info.cmd is not None else None
                ),
                "noop" if value.is_noop else tuple(
                    sorted(dep.dot for dep in value.deps)
                ),
            )
        if info.cmd is not None:
            meta = {"noop": True} if value.is_noop else None
            if (
                not value.is_noop
                and self.bp.tracer.enabled
                and self.bp.tracer.sample(info.cmd.rifl)
            ):
                # stamp the agreed dep set (capped) so the critical-path
                # walk can name WHICH dot the executor then waited on
                # (observability/critpath.py dep-wait blame); meta built
                # only for sampled spans — it costs a sort per commit
                deps = sorted(dep.dot for dep in value.deps)
                if deps:
                    meta = {"deps": [[d[0], d[1]] for d in deps[:16]]}
                    if len(deps) > 16:
                        meta["deps_total"] = len(deps)
            self.bp.trace_span("commit", info.cmd.rifl, dot=dot, meta=meta)
        out = info.synod.handle(from_, MChosen(value))
        assert out is None
        self._recovery_untrack(dot)
        if self._gc_running() and self._dot_in_my_shard(dot):
            self._to_processes.append(ToForward(MCommitDot(dot)))
        else:
            self._cmds.gc_single(dot)

    def _handle_mconsensus(self, from_, dot, ballot, value, cmd=None, time=None) -> None:
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None:
            self._adopt_recovered_payload(dot, info, cmd, time)
        out = info.synod.handle(from_, MAccept(ballot, value))
        if out is None:
            return  # ballot too low
        if isinstance(out, SynodMAccepted):
            self._to_processes.append(ToSend({from_}, MConsensusAck(dot, out.ballot)))
        elif isinstance(out, MChosen):
            # already chosen here (late MConsensus): replying the *local*
            # value is only sound single-shard — a multi-shard MCommit must
            # carry the cross-shard aggregate, which travels through
            # MShardAggregatedCommit (the coordinator's ack path)
            if info.cmd is None or info.cmd.shard_count == 1:
                self._to_processes.append(
                    ToSend({from_}, MCommit(dot, out.value, cmd=info.cmd))
                )
        else:
            raise AssertionError(f"unexpected synod output {out}")

    def _handle_mconsensusack(self, from_, dot, ballot) -> None:
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        out = info.synod.handle(from_, SynodMAccepted(ballot))
        if out is None:
            return
        assert isinstance(out, MChosen), f"unexpected synod output {out}"
        self._mcommit_actions(dot, out.value)

    def _mcommit_actions(self, dot: Dot, value: ConsensusValue) -> None:
        """Single-shard: broadcast MCommit.  Multi-shard: route the decided
        deps through the shard-commit aggregation (partial.rs:37-102)."""
        info = self._cmds.get(dot)
        cmd = info.cmd
        if cmd is None or not self.partial_mcommit_actions(dot, cmd, set(value.deps)):
            self._to_processes.append(ToSend(self.bp.all(), MCommit(dot, value)))

    # --- recovery hooks (protocol/recovery.py) ---

    def _adopt_recovered_payload(self, dot, info, cmd, time) -> None:
        info.cmd = cmd
        if info.status == Status.START:
            info.status = Status.PAYLOAD
            self._replay_buffered_commit(dot, time)

    def _recovery_commit_known(self, dot) -> bool:
        return dot in self._buffered_commits

    def _recovery_consensus_msg(self, dot, ballot, value, cmd):
        return MConsensus(dot, ballot, value, cmd)

    def _recovery_chosen_reply(self, to, dot, info, value) -> None:
        # same single-shard guard as the late-MConsensus reply: multi-shard
        # commits must carry the cross-shard aggregate.  The payload rides
        # along: the asker may hold a payload-less buffered commit
        if info.cmd is None or info.cmd.shard_count == 1:
            self._to_processes.append(
                ToSend({to}, MCommit(dot, value, cmd=info.cmd))
            )

    # --- rejoin sync hooks (protocol/sync.py) ---

    def _sync_record(self, dot, info):
        # the decided value lives in the per-dot synod once MChosen ran
        # (commit bookkeeping); cmd is None for recovered noops
        return (dot, info.cmd, info.synod.value())

    def _apply_sync_record(self, from_, record, time) -> None:
        dot, cmd, value = record
        if self._gc_track.contains(dot):
            return  # committed (and possibly executed + GC'd) here already
        info = self._cmds.get(dot)
        if info.status == Status.COMMIT:
            return
        if cmd is not None and info.cmd is None:
            self._adopt_recovered_payload(dot, info, cmd, time)
        self._handle_mcommit(from_, dot, value, time)

    # --- partial-replication adapters (deps union; atlas.rs:559-650) ---

    def _partial_initial_data(self):
        return set()

    def _partial_join(self, acc, data):
        return acc | set(data)

    def _partial_final_mcommit(self, dot: Dot, data, _local):
        return MCommit(dot, ConsensusValue(set(data)))

    def _dot_in_my_shard(self, dot: Dot) -> bool:
        return dot.target_shard(self.bp.config.n) == self.bp.shard_id

    # --- worker routing (epaxos.rs:704-740) ---

    @staticmethod
    def message_index(msg):
        if isinstance(
            msg,
            (
                MCollect,
                MCollectAck,
                MCommit,
                MConsensus,
                MConsensusAck,
                MForwardSubmit,
                MShardCommit,
                MShardAggregatedCommit,
                MRecoveryPrepare,
                MRecoveryPromise,
            ),
        ):
            return worker_dot_index_shift(msg.dot)
        if isinstance(msg, (MSync, MSyncReply, MSyncBackfill)):
            # dotless rejoin traffic: serialized on the GC worker (whose
            # committed clock it reads and whose retention it rides)
            return worker_index_no_shift(GC_WORKER_INDEX)
        gc_index = CommitGCMixin.gc_message_index(msg)
        if gc_index is not None:
            return gc_index[0]
        raise AssertionError(f"unknown message {msg}")


class EPaxos(GraphProtocol):
    """EPaxos: fast path iff *all* fast-quorum deps are equal; always
    tolerates a minority of faults (epaxos.rs:27-972)."""

    @classmethod
    def allowed_faults(cls, n: int) -> int:
        return n // 2

    @classmethod
    def quorum_sizes(cls, config: Config) -> Tuple[int, int]:
        return config.epaxos_quorum_sizes()

    @classmethod
    def consensus_f(cls, config: Config) -> int:
        return cls.allowed_faults(config.n)

    @classmethod
    def coordinator_self_ack(cls) -> bool:
        # the coordinator's deps don't join the fast-path check: the tracker
        # is sized fast_quorum_size - 1 and self-acks are never produced
        return False

    def fast_path_condition(self, info):
        return info.quorum_deps.check_union()


class Atlas(GraphProtocol):
    """Atlas: fast quorum n//2 + f; fast path via threshold union — every
    dependency reported at least f times (atlas.rs:28-1143).  Supports
    partial replication (MForwardSubmit / MShardCommit /
    MShardAggregatedCommit, atlas.rs:157-165)."""

    @classmethod
    def supports_partial_replication(cls) -> bool:
        return True

    @classmethod
    def quorum_sizes(cls, config: Config) -> Tuple[int, int]:
        return config.atlas_quorum_sizes()

    @classmethod
    def consensus_f(cls, config: Config) -> int:
        return config.f

    @classmethod
    def coordinator_self_ack(cls) -> bool:
        return True

    def fast_path_condition(self, info):
        return info.quorum_deps.check_threshold_union(self.bp.config.f)
