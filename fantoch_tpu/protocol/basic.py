"""Basic protocol: store at f+1 replicas, then commit.

Reference: fantoch/src/protocol/basic.rs:20-395.  Deliberately inconsistent
(no real consensus) — it exists to exercise the full machinery end-to-end:
submit -> MStore to fast quorum -> f+1 MStoreAck -> MCommit to all ->
per-key execution info, plus the complete GC message set
(MCommitDot/MGarbageCollection/MStable) shared by all leaderless protocols.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Optional, Set

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.basic import BasicExecutionInfo, BasicExecutor
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.commit_gc import (
    CommitGCMixin,
    GarbageCollectionEvent,
    MCommitDot,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo
from fantoch_tpu.run.routing import worker_dot_index_shift


# --- messages ---


@dataclass
class MStore:
    dot: Dot
    cmd: Command


@dataclass
class MStoreAck:
    dot: Dot


@dataclass
class MCommit:
    dot: Dot
    cmd: Command


def _basic_info_factory(*_args) -> "BasicInfo":
    """Module-level (picklable) per-dot info factory: the model checker
    copies protocol state by pickling, which lambdas would break."""
    return BasicInfo()


@dataclass
class BasicInfo:
    """Per-dot lifecycle info (basic.rs:318-341)."""

    cmd: Optional[Command] = None
    acks: Set[ProcessId] = field(default_factory=set)


class Basic(CommitGCMixin, Protocol):
    Executor = BasicExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        fast_quorum_size = config.basic_quorum_size()
        write_quorum_size = 0  # 100% fast paths: no write quorum
        self.bp = BaseProcess(process_id, shard_id, config, fast_quorum_size, write_quorum_size)
        self._cmds: CommandsInfo[BasicInfo] = CommandsInfo(
            process_id,
            shard_id,
            config,
            fast_quorum_size,
            write_quorum_size,
            _basic_info_factory,
        )
        self._gc_track = GCTrack(process_id, shard_id, config.n)
        self._to_processes: deque = deque()
        self._to_executors: deque = deque()

    def periodic_events(self):
        return self.gc_periodic_events()

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        self._to_processes.append(ToSend(self.bp.fast_quorum(), MStore(dot, cmd)))

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MStore):
            self._handle_mstore(from_, msg.dot, msg.cmd)
        elif isinstance(msg, MStoreAck):
            self._handle_mstoreack(from_, msg.dot)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.cmd)
        elif not self.handle_gc_message(from_, msg):
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        assert isinstance(event, GarbageCollectionEvent)
        self.handle_gc_event()

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        return self._to_executors.popleft() if self._to_executors else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    @classmethod
    def leaderless(cls) -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_mstore(self, from_: ProcessId, dot: Dot, cmd: Command) -> None:
        info = self._cmds.get(dot)
        info.cmd = cmd
        self._to_processes.append(ToSend({from_}, MStoreAck(dot)))

    def _handle_mstoreack(self, from_: ProcessId, dot: Dot) -> None:
        info = self._cmds.get(dot)
        info.acks.add(from_)
        if len(info.acks) == self.bp.config.basic_quorum_size():
            assert info.cmd is not None, "command should exist"
            self._to_processes.append(ToSend(self.bp.all(), MCommit(dot, info.cmd)))

    def _handle_mcommit(self, _from: ProcessId, dot: Dot, cmd: Command) -> None:
        info = self._cmds.get(dot)
        info.cmd = cmd
        self.bp.audit_commit(dot, cmd.rifl, None)
        # one execution info per key: lets the basic executor run key-parallel
        rifl = cmd.rifl
        for key, ops in cmd.iter_ops(self.bp.shard_id):
            self._to_executors.append(BasicExecutionInfo(rifl, key, ops))
        if self._gc_running():
            self._to_processes.append(ToForward(MCommitDot(dot)))
        else:
            self._cmds.gc_single(dot)

    # --- worker routing (basic.rs:354-384) ---

    @staticmethod
    def message_index(msg):
        if isinstance(msg, (MStore, MStoreAck, MCommit)):
            return worker_dot_index_shift(msg.dot)
        gc_index = CommitGCMixin.gc_message_index(msg)
        if gc_index is not None:
            return gc_index[0]
        raise AssertionError(f"unknown message {msg}")
