"""Breadth-first explicit-state model checking of protocol + executor.

Reference: ``fantoch_mc`` (fantoch_mc/src/lib.rs:75-120) adapts a
``(Protocol, Executor)`` pair to a stateright ``Actor``; stateright then
enumerates message-delivery interleavings and checks user properties.
That crate is bit-rotted (pre-shard API) and disabled upstream — this is
a working equivalent, self-contained because our protocols are plain
deterministic Python objects that deepcopy/pickle cleanly.

Model:

* a **state** is (protocol instances, executor instances, network
  multiset of in-flight messages, not-yet-submitted commands, per-process
  executed results);
* **actions**: submit any unsubmitted command at its coordinator, or
  deliver any in-flight message (in any order — the network reorders
  arbitrarily but neither drops nor duplicates, matching the simulator's
  delivery model, fantoch/src/sim/runner.rs:514-518);
* successors are explored breadth-first with a visited set keyed on a
  canonical *value* fingerprint (identity- and history-blind), so
  converging interleavings merge regardless of how they were reached.

Checked properties (the reference harness's assertions,
fantoch_ps/src/protocol/mod.rs:924-1010, turned into MC invariants):

* **safety, every state**: per-key execution orders across processes are
  pairwise prefix-compatible (linearizable agreement — a divergence shows
  up as soon as it happens, with a minimal-length trace);
* **terminal states** (no messages in flight, everything submitted):
  every process executed every command on every key it owns, and the
  per-key orders are identical.

Periodic events (GC, detached votes, executed notifications) run only at
**quiescence**, as a DETERMINISTIC stabilization closure: once no submit
or delivery is enabled, every process's timers fire in sorted order and
the resulting messages drain FIFO, repeated to a fixpoint
(:meth:`ModelChecker._stabilize`).  Timer-order interleavings are NOT
branched over — a deliberate reduction that keeps the space small while
still running the timer-driven paths (Newt's detached-vote stability,
Caesar's executor-driven GC, the GC message flow) to their steady state
on top of every explored workload interleaving.  This mirrors how the
reference's sim tests drive timers: extra_sim_time after the workload
(sim/runner.rs:203).
"""

from __future__ import annotations

import pickle
import types
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ProcessId
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.protocol.base import ToForward, ToSend


def _canonical(obj, depth: int = 0):
    """Recursively transform ``obj`` into a pure value structure (nested
    tuples of primitives) whose ``repr`` is identical for logically-equal
    inputs regardless of object identity or container insertion history.

    Plain pickling is NOT canonical: the pickler memoizes shared objects
    (an aliased Dot serializes as a memo reference, an equal-but-distinct
    one as a full body) and sets/dicts serialize in history-dependent
    iteration order — logically-equal states would fingerprint differently
    and be explored redundantly (sound — never merges distinct states —
    but wasteful and copy-regime-dependent)."""
    if depth > 60:  # pathological nesting: degrade to repr
        return repr(obj)
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, types.FunctionType):
        return f"<fn {obj.__module__}.{obj.__qualname__}>"
    if isinstance(obj, (list, tuple, deque)):
        return (
            type(obj).__name__,
            tuple(_canonical(e, depth + 1) for e in obj),
        )
    if isinstance(obj, (set, frozenset)):
        elems = [_canonical(e, depth + 1) for e in obj]
        return ("set", tuple(sorted(elems, key=repr)))
    if isinstance(obj, dict):
        items = [
            (_canonical(k, depth + 1), _canonical(v, depth + 1))
            for k, v in obj.items()
        ]
        return ("dict", tuple(sorted(items, key=lambda kv: repr(kv[0]))))
    # arbitrary object: class identity + canonical attribute state
    state = getattr(obj, "__dict__", None)
    if state is None:
        slots = []
        for klass in type(obj).__mro__:
            slots.extend(getattr(klass, "__slots__", ()))
        state = {s: getattr(obj, s) for s in slots if hasattr(obj, s)}
    if not state:
        # C-implemented objects (functools.partial, bound methods, ...)
        # keep their payload outside __dict__/__slots__: an empty-state
        # fingerprint would unsoundly merge distinct values, so
        # canonicalize their reduce form (or degrade to repr)
        try:
            state = obj.__reduce_ex__(2)
        except Exception:  # noqa: BLE001
            return repr(obj)
    return (
        f"{type(obj).__module__}.{type(obj).__qualname__}",
        _canonical(state, depth + 1),
    )


def _dumps(obj) -> bytes:
    """Canonical fingerprint bytes: value-determined, identity-blind."""
    return repr(_canonical(obj)).encode()


@dataclass
class Violation:
    kind: str  # "agreement" | "incomplete" | "divergent_terminal"
    detail: str
    trace: List[str]  # action descriptions from the initial state


@dataclass
class CheckResult:
    states: int
    transitions: int
    terminals: int
    violations: List[Violation]
    complete: bool  # exhausted the space (False = hit max_states)

    @property
    def ok(self) -> bool:
        return not self.violations


class _State:
    __slots__ = (
        "protocols",
        "executors",
        "network",
        "unsubmitted",
        "executed",
        "crashed",
        "optional",
    )

    def __init__(
        self, protocols, executors, network, unsubmitted, executed,
        crashed=(), optional=(),
    ):
        self.protocols: Dict[ProcessId, Any] = protocols
        self.executors: Dict[ProcessId, Any] = executors
        # in-flight messages: (from_pid, to_pid, msg, fingerprint) — the
        # fingerprint is computed once at send time (messages are copied
        # at send and never mutated in flight)
        self.network: List[Tuple[ProcessId, ProcessId, Any, bytes]] = network
        self.unsubmitted: List[Tuple[ProcessId, Command]] = unsubmitted
        # per-process executed (rifl) order, per key — the agreement object
        self.executed: Dict[ProcessId, Dict[str, List[Any]]] = executed
        # crashed process ids (sorted tuple): they take no actions, their
        # inbound messages evaporate — the nemesis crash, in MC form
        self.crashed: Tuple[ProcessId, ...] = tuple(crashed)
        # rifls submitted at a now-crashed coordinator: survivors must
        # execute them everywhere or nowhere (recovery may noop them)
        self.optional: Tuple[Any, ...] = tuple(optional)


class ModelChecker:
    """Exhaustive small-scope checker for one protocol class.

    ``submits``: list of (coordinator process id, Command); every
    interleaving of submissions and deliveries is explored.
    """

    def __init__(
        self,
        protocol_cls,
        config: Config,
        submits: List[Tuple[ProcessId, Command]],
        max_states: int = 200_000,
        check_agreement: bool = True,
        crashes: Optional[List[ProcessId]] = None,
    ):
        self._protocol_cls = protocol_cls
        self._config = config
        self._submits = submits
        self._max_states = max_states
        # processes that MAY crash: exploration branches a crash action for
        # each at every state (once per process), so every
        # crash-interleaving is covered.  Crash semantics mirror the sim
        # nemesis: in-flight messages to the dead process evaporate, it
        # takes no further actions, and its not-yet-submitted commands are
        # abandoned with it.  Pair with Config.recovery_delay_ms so the
        # stabilization closure drives MPrepare/MPromise recovery of its
        # in-flight dots.
        self._crashes = list(crashes or [])
        # Basic is the reference's intentionally *inconsistent* protocol
        # (fantoch/src/protocol/basic.rs): per-key agreement is not among
        # its properties, so callers disable that invariant for it
        self._check_agreement_flag = check_agreement
        # copy regime: pickle round-trip while it works, with a lazy
        # one-way downgrade to deepcopy on the first pickle failure
        # (per-instance, warned once).  With alias-free messages (_drain)
        # and value-canonical fingerprints the two regimes explore the
        # exact same state space, so the downgrade is purely a speed loss
        self._use_pickle_copy = True
        self._time = SimTime()  # fixed logical time: delivery order is the model

    # --- state construction ---

    def _initial_state(self) -> _State:
        n = self._config.n
        from fantoch_tpu.core.ids import process_ids

        ids = list(process_ids(0, n))
        protocols, executors = {}, {}
        for pid in ids:
            proto = self._protocol_cls(pid, 0, self._config)
            # self-first discover list, deterministic topology
            sorted_procs = [(pid, 0)] + [(p, 0) for p in ids if p != pid]
            ok, _ = proto.discover(sorted_procs)
            assert ok
            protocols[pid] = proto
            executor = self._protocol_cls.Executor(pid, 0, self._config)
            executor.set_executor_index(0)
            executors[pid] = executor
        return _State(
            protocols,
            executors,
            [],
            list(self._submits),
            {pid: {} for pid in ids},
        )

    # --- actions ---

    def _enabled(self, st: _State) -> List[Tuple[str, Any]]:
        actions: List[Tuple[str, Any]] = []
        for i, (pid, cmd) in enumerate(st.unsubmitted):
            if pid not in st.crashed:
                actions.append(("submit", i))
        seen = set()
        for i, (src, dst, _msg, fp) in enumerate(st.network):
            # identical in-flight messages are interchangeable: exploring
            # one of them covers all (multiset symmetry reduction)
            key = (src, dst, fp)
            if key not in seen:
                seen.add(key)
                actions.append(("deliver", i))
        for pid in self._crashes:
            if pid not in st.crashed:
                actions.append(("crash", pid))
        return actions

    def _apply(self, st: _State, action: Tuple[str, Any]) -> Tuple[_State, str]:
        succ = self._copy_state(st)
        return succ, self._apply_to(succ, action)

    def _copy_state(self, st: _State) -> _State:
        """Pickle round-trip (~3x faster than deepcopy for these object
        graphs — the protocol info factories are module-level precisely so
        state pickles).  Equivalent to deepcopy because messages are copied
        at send time (_drain), so states carry no cross-object aliases; a
        pickle failure downgrades THIS checker instance for the rest of
        its run (per-instance, so one exotic protocol cannot change the
        copy regime of later checkers in the process)."""
        if self._use_pickle_copy:
            try:
                protocols, executors, network, executed = pickle.loads(
                    pickle.dumps(
                        (st.protocols, st.executors, st.network, st.executed),
                        protocol=pickle.HIGHEST_PROTOCOL,
                    )
                )
                return _State(
                    protocols, executors, network, list(st.unsubmitted), executed,
                    st.crashed, st.optional,
                )
            except Exception as exc:  # noqa: BLE001 — unpicklable: degrade
                import warnings

                warnings.warn(
                    f"model checker falling back to deepcopy state copies "
                    f"(~3x slower): state refused to pickle: {exc!r}",
                    RuntimeWarning,
                    stacklevel=2,
                )
                self._use_pickle_copy = False
        import copy

        return _State(
            copy.deepcopy(st.protocols),
            copy.deepcopy(st.executors),
            copy.deepcopy(st.network),
            list(st.unsubmitted),
            copy.deepcopy(st.executed),
            st.crashed,
            st.optional,
        )

    def _apply_to(self, succ: _State, action: Tuple[str, Any]) -> str:
        """Apply ``action`` to ``succ`` in place; returns the description.
        Branching exploration copies first (_apply); the linear
        stabilization closure mutates one working copy."""
        kind, i = action
        if kind == "submit":
            pid, cmd = succ.unsubmitted.pop(i)
            succ.protocols[pid].submit(None, cmd, self._time)
            self._drain(succ, pid)
            desc = f"submit {cmd.rifl} at p{pid}"
        elif kind == "crash":
            pid = i
            # nemesis semantics: already-sent messages from the dead
            # process stay deliverable; everything addressed to it
            # evaporates; its unsubmitted commands are abandoned with it
            succ.crashed = tuple(sorted({*succ.crashed, pid}))
            succ.network = [e for e in succ.network if e[1] != pid]
            submitted_here = {
                cmd.rifl for p, cmd in self._submits if p == pid
            } - {cmd.rifl for p, cmd in succ.unsubmitted if p == pid}
            succ.optional = tuple(
                sorted({*succ.optional, *submitted_here}, key=repr)
            )
            succ.unsubmitted = [e for e in succ.unsubmitted if e[0] != pid]
            desc = f"crash p{pid}"
        elif kind == "events":
            pid = i
            proto = succ.protocols[pid]
            for event, _interval in proto.periodic_events():
                proto.handle_event(event, self._time)
            executed = succ.executors[pid].executed(self._time)
            if executed is not None:
                proto.handle_executed(executed, self._time)
            self._drain(succ, pid)
            desc = f"periodic events at p{pid}"
        else:
            src, dst, msg, _fp = succ.network.pop(i)
            succ.protocols[dst].handle(src, 0, msg, self._time)
            self._drain(succ, dst)
            desc = f"deliver {type(msg).__name__} {src}->{dst}"
        return desc

    def _drain(self, st: _State, pid: ProcessId) -> None:
        """Collect a process's outputs: peer messages enter the reorderable
        network; self-addressed messages (self∈ToSend target, ToForward)
        are handled synchronously, exactly like the reference runner's
        local fast path (fantoch/src/run/task/process.rs:591-678) and the
        simulator's zero-latency self hop — protocols rely on it (e.g. a
        coordinator's own MCollectAck can never trail a peer's)."""
        import copy

        local = deque()
        proto = st.protocols[pid]
        executor = st.executors[pid]

        def pump() -> None:
            for act in proto.to_processes_iter():
                if isinstance(act, ToSend):
                    # copy EVERY outgoing message, first target included: a
                    # message object may alias sender state (e.g. Newt's
                    # MCommit carries info.votes), and the real network
                    # serializes per send — an aliased in-flight message
                    # would let a receiver mutate the sender's state across
                    # the process boundary, and would also make the
                    # pickle-round-trip state copy (alias-preserving) differ
                    # from per-field deepcopy (alias-severing)
                    for target in sorted(act.target):
                        if target in st.crashed:
                            continue  # dead endpoint: the message evaporates
                        msg = copy.deepcopy(act.msg)
                        if target == pid:
                            local.append(msg)
                        else:
                            st.network.append((pid, target, msg, _dumps(msg)))
                elif isinstance(act, ToForward):
                    local.append(copy.deepcopy(act.msg))
                else:  # pragma: no cover
                    raise AssertionError(f"unknown action {act}")
            # route through the batch seam when the executor has one: the
            # model checker then exhaustively verifies the batched path's
            # equivalence to the per-info path across every interleaving
            # (batch sizes vary with how many infos each pump finds)
            infos = list(proto.to_executors_iter())
            if infos:
                handle_batch = getattr(executor, "handle_batch", None)
                if handle_batch is not None:
                    handle_batch(infos, self._time)
                else:
                    for info in infos:
                        executor.handle(info, self._time)
            for result in executor.to_clients_iter():
                st.executed[pid].setdefault(result.key, []).append(result.rifl)

        pump()
        while local:
            proto.handle(pid, 0, local.popleft(), self._time)
            pump()

    # --- invariants ---

    @staticmethod
    def _check_agreement(st: _State) -> Optional[Tuple[str, str]]:
        """Per-key orders must be pairwise prefix-compatible at all times.
        Returns (kind, detail) or None."""
        pids = sorted(st.executed)
        for a_i, a in enumerate(pids):
            for b in pids[a_i + 1 :]:
                for key, order_a in st.executed[a].items():
                    order_b = st.executed[b].get(key, [])
                    short = min(len(order_a), len(order_b))
                    if order_a[:short] != order_b[:short]:
                        return (
                            "agreement",
                            f"key {key!r}: p{a} executed {order_a[:short]} "
                            f"but p{b} executed {order_b[:short]}",
                        )
        return None

    def _check_terminal(self, st: _State) -> Optional[Tuple[str, str]]:
        """Nothing in flight: every surviving process executed every
        mandatory command; commands whose coordinator crashed mid-run
        (``st.optional``) execute everywhere or nowhere (recovery may have
        nooped them).  Returns (kind, detail) or None."""
        optional = set(st.optional)
        survivors = [pid for pid in sorted(st.executed) if pid not in st.crashed]
        # mandatory rifls per key: submitted commands whose coordinator
        # survived (recovery guarantees their completion); never-submitted
        # commands of a crashed coordinator are not in either set
        mandatory: Dict[str, set] = {}
        for pid, cmd in self._submits:
            if pid in st.crashed or cmd.rifl in optional:
                continue
            for key in cmd.keys(0):
                mandatory.setdefault(key, set()).add(cmd.rifl)
        for pid in survivors:
            by_key = st.executed[pid]
            for key, rifls in mandatory.items():
                got = set(by_key.get(key, []))
                if not rifls <= got:
                    return (
                        "incomplete",
                        f"p{pid} missed mandatory {sorted(rifls - got, key=repr)} "
                        f"on key {key!r} in a terminal state",
                    )
        if self._check_agreement_flag and survivors:
            first = st.executed[survivors[0]]
            for pid in survivors[1:]:
                if st.executed[pid] != first:
                    return (
                        "divergent_terminal",
                        f"terminal orders diverge: p{survivors[0]}={first} "
                        f"p{pid}={st.executed[pid]}",
                    )
        # GC completeness (the reference's gc_at x commits == stable check,
        # fantoch_ps/src/protocol/mod.rs:1060-1075, as a structural
        # invariant): with GC configured, a stabilized terminal must have
        # drained every per-dot info.  A crash legitimately halts GC (the
        # dead process stops reporting its committed clock), so the
        # invariant only applies to crash-free runs
        if self._config.gc_interval_ms is not None and not st.crashed:
            for pid, proto in st.protocols.items():
                infos = getattr(getattr(proto, "_cmds", None), "_infos", None)
                if infos:
                    return (
                        "incomplete",
                        f"p{pid} holds {len(infos)} un-GC'd infos in a "
                        f"stabilized terminal: {sorted(infos)[:4]}",
                    )
        return None

    # --- quiescence stabilization ---

    def _stabilize(self, st: _State, max_rounds: int = 32) -> Tuple[_State, bool]:
        """Deterministic timer closure from a quiescent state: fire every
        process's periodic events + executed notification (sorted order),
        drain the resulting messages FIFO, repeat until nothing changes.
        Models "after the network drains, timers keep firing" — the same
        regime as the reference sim's extra_sim_time tail
        (sim/runner.rs:203), where periodic GC/detached/executed events
        run the system to its steady state.  Timer-order interleavings are
        NOT branched over (a deliberate reduction; delivery interleavings
        of the actual workload are fully explored before quiescence).

        Returns ``(state, converged)``: ``converged`` is False when
        ``max_rounds`` elapsed without reaching a fingerprint fixpoint —
        terminal invariants checked on such a state may be spurious, so
        callers must mark any violation found there as truncated.

        Crashed processes take no timer actions.  Stabilization runs on a
        far-future clock so time-gated timers actually fire — in
        particular the per-dot recovery scan (Config.recovery_delay_ms),
        which is how a crashed coordinator's in-flight dots heal through
        MPrepare/MPromise inside the closure."""
        succ = self._copy_state(st)
        outer_time = self._time
        try:
            prev_fp = self._fingerprint(succ)
            converged = False
            for round_index in range(max_rounds):
                # the clock ADVANCES by a full far-future stride per round:
                # time-gated retry ladders (the per-dot recovery scan's
                # owner-first stagger and the free-choice full-quorum hold's
                # round release, protocol/recovery.py) re-arm on elapsed
                # time, so a frozen clock would fire each of them exactly
                # once and a held recovery could never fall back to n - f
                self._time = SimTime(1_000_000_000 * (round_index + 1))
                for pid in sorted(succ.protocols):
                    if pid not in succ.crashed:
                        self._apply_to(succ, ("events", pid))
                while succ.network:
                    self._apply_to(succ, ("deliver", 0))
                fp = self._fingerprint(succ)
                if fp == prev_fp:
                    converged = True
                    break
                prev_fp = fp
        finally:
            self._time = outer_time
        return succ, converged

    # --- exploration ---

    @staticmethod
    def _fingerprint(st: _State) -> bytes:
        return _dumps(
            (
                sorted(st.protocols.items(), key=lambda kv: kv[0]),
                sorted(st.executors.items(), key=lambda kv: kv[0]),
                sorted((s, d, fp) for s, d, _m, fp in st.network),
                st.unsubmitted,
                sorted(st.executed.items()),
                st.crashed,
                st.optional,
            )
        )

    def run(self) -> CheckResult:
        initial = self._initial_state()
        visited = {self._fingerprint(initial)}
        # frontier holds (state, trace); traces stay short (depth <= total
        # actions = submits + messages ever sent)
        frontier = deque([(initial, [])])
        states = transitions = terminals = 0
        violations: List[Violation] = []
        complete = True

        while frontier:
            if states >= self._max_states:
                complete = False
                break
            st, trace = frontier.popleft()
            states += 1

            bad = self._check_agreement(st) if self._check_agreement_flag else None
            if bad is not None:
                violations.append(Violation(bad[0], bad[1], trace))
                continue  # don't explore past a violated state

            actions = self._enabled(st)
            if all(kind == "crash" for kind, _ in actions):
                # quiescence: no submit/delivery left (a crash from a fully
                # quiescent state is not explored — nothing is in flight, so
                # it cannot change any surviving invariant): stabilize
                # deterministically (timers + FIFO drains to a fixpoint),
                # then check the terminal invariants
                terminals += 1
                stable, converged = self._stabilize(st)
                if not converged:
                    # invariants checked on a truncated stabilization are
                    # unreliable in both directions: a violation may be
                    # spurious AND a real one may not have materialized yet
                    # — so the exploration cannot claim completeness
                    complete = False
                bad = self._check_agreement(stable) if self._check_agreement_flag else None
                if bad is None:
                    bad = self._check_terminal(stable)
                if bad is not None:
                    detail = bad[1]
                    if not converged:
                        detail += (
                            " [stabilization truncated at max_rounds without"
                            " a fixpoint; this violation may be spurious]"
                        )
                    violations.append(
                        Violation(bad[0], detail, trace + ["<stabilize>"])
                    )
                continue

            for action in actions:
                succ, desc = self._apply(st, action)
                transitions += 1
                fp = self._fingerprint(succ)
                if fp not in visited:
                    visited.add(fp)
                    frontier.append((succ, trace + [desc]))

        return CheckResult(states, transitions, terminals, violations, complete)
