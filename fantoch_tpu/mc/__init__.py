"""Explicit-state model checker over (Protocol, Executor) pairs.

The working analog of the reference's ``fantoch_mc`` crate
(fantoch_mc/src/lib.rs:75-120), which wraps a protocol as a stateright
Actor but is bit-rotted and excluded from the reference workspace
(Cargo.toml:10).  This checker explores every interleaving of command
submissions and message deliveries for a small cluster and workload,
checking safety at every state and execution completeness at terminal
states.  See :mod:`fantoch_tpu.mc.checker`.
"""

from fantoch_tpu.mc.checker import CheckResult, ModelChecker, Violation

__all__ = ["CheckResult", "ModelChecker", "Violation"]
