"""Device-resident predecessors plane for Caesar's two-phase ordering.

The host twin (:class:`~fantoch_tpu.executor.pred.PredecessorsGraph`)
resolves the two-phase countdown per vertex in Python; the batched seam
(``ops/pred_resolve.resolve_pred``) kernels one batch but re-uploads it
from scratch every call and hands any blocked residue back to the host
indexes.  This plane is the table-plane move applied to Caesar (ROADMAP
item 4 on the item-5 base): the whole pending window — sparse predecessor
sets as a resident ``int32[C, W]`` slot matrix plus (clock, src, occ,
executed) columns — lives ON DEVICE across batches with donated in-place
state (``ops/pred_resolve.resolve_pred_plane_step``), and each executor
feed is ONE dispatch that installs the new commits, re-points the dep
cells whose missing dots just arrived, and runs the two-phase fixpoint
over everything still pending.

Residual protocol: a missing-blocked row (a dependency not committed
here yet) stays resident — its ``MISSING`` cells are patched when the
dep commits in a later feed (or resolves as a recovered noop), mirroring
the table plane's beyond-gap runs re-feeding until the gap fills.

Host bookkeeping is COLUMN-NATIVE (the PR 4 arrays discipline): dots are
packed int64s, installs/emissions are vectorized numpy over the feed,
and the only per-item host work is one dict probe per dependency.  Slots
are never refcounted: allocation is a bump pointer, and when the window
fills the plane compacts — still-pending rows re-pack to the bottom
(dep cells remapped through one LUT; cells referencing executed rows
fold to ``TERMINAL``) in one fetch + counted re-upload, the same
peel-and-compact discipline as the general-path resolver.

Buffer lifecycle — donation-safe uploads, lazy host-mirror
re-materialization after restore with exactly ONE counted re-upload,
pow2 capacity growth, per-dispatch counters — is the shared
:class:`~fantoch_tpu.executor.device_plane.DevicePlane` base.

Clock width: device clocks are int32; the plane refuses timestamp
sequences at or above ``2^31 - 1`` with the shared typed error.
"""

from __future__ import annotations

import time as _time
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, all_process_ids
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import ExecutorMetricsKind
from fantoch_tpu.errors import DeviceCorruptionError, DeviceFailedError
from fantoch_tpu.executor.device_plane import DevicePlane, next_pow2 as _pow2
from fantoch_tpu.executor.table_plane import ClockOverflowError
from fantoch_tpu.protocol.common.pred_clocks import Clock

_INT32_MAX = (1 << 31) - 1

# packed dot id: (source << 40) | sequence — sources are small ints,
# sequences are per-source counters (the ops/frontier.pack_dots shape)
_PACK_SHIFT = 40


def _pack(src: int, seq: int) -> int:
    return (src << _PACK_SHIFT) | seq


def _pack_cols(src: np.ndarray, seq: np.ndarray) -> np.ndarray:
    return (src.astype(np.int64) << _PACK_SHIFT) | seq.astype(np.int64)


class DevicePredPlane(DevicePlane):
    """Resident two-phase predecessor window + one fused dispatch per
    executor feed.  Drop-in for the ``PredecessorsGraph`` surface the
    :class:`~fantoch_tpu.executor.pred.PredecessorsExecutor` drives
    (add/add_batch/handle_noop/command_to_execute/executed/metrics/
    monitor_pending) — oracle-equivalence tested per key against the
    host twin (tests/test_pred_plane.py)."""

    __slots__ = (
        "_process_id",
        "_config",
        "_width",
        "_next_slot",
        "_executed_clock",
        "_exec_recent",
        "_slot_of",
        "_slot_src",
        "_slot_seq",
        "_slot_start",
        "_slot_cseq",
        "_slot_csrc",
        "_slot_cmd",
        "_waiters",
        "_waiter_since",
        "_metrics",
        "_to_execute",
    )

    plane_name = "pred"

    def __init__(
        self,
        process_id: ProcessId,
        config: Config,
        slot_capacity: int = 1024,
        width: int = 4,
    ):
        super().__init__(
            slot_capacity,
            stats={
                # per-dispatch tallies: new_rows/update_capacity is the
                # install-batch occupancy (padding waste), residual_rows
                # the still-blocked window after the dispatch, kernel_ms
                # the blocking dispatch+transfer wall time; compactions
                # counts window re-packs (each is one counted re-upload)
                "new_rows": 0,
                "update_capacity": 0,
                "residual_rows": 0,
                "compactions": 0,
                "kernel_ms": 0.0,
            },
        )
        ids = [pid for pid, _ in all_process_ids(config.shard_count, config.n)]
        self._process_id = process_id
        self._config = config
        self._width = _pow2(max(width, 1))
        self._next_slot = 0
        # the GC-facing executed clock (compact range encoding), fed by
        # batched add_range at emission; _exec_recent is the flat probe
        # set for encode-time dep checks (cleared at compaction, so it is
        # bounded by the compaction cadence — older dots fall back to the
        # clock's bisect)
        self._executed_clock: AEClock = AEClock(ids)
        self._exec_recent: Set[int] = set()
        # packed dot -> slot, PENDING rows only (emission pops)
        self._slot_of: Dict[int, int] = {}
        # per-slot host columns (vectorized install/emission)
        self._slot_src = np.zeros(self._cap, dtype=np.int64)
        self._slot_seq = np.zeros(self._cap, dtype=np.int64)
        self._slot_start = np.zeros(self._cap, dtype=np.int64)
        # timestamp columns mirrored host-side: execution order among one
        # dispatch's newly-executed rows is a host lexsort over these (a
        # dynamic-size sort over the executed handful, instead of a
        # full-capacity device sort per dispatch)
        self._slot_cseq = np.zeros(self._cap, dtype=np.int64)
        self._slot_csrc = np.zeros(self._cap, dtype=np.int64)
        self._slot_cmd: Dict[int, Command] = {}
        # missing packed dot -> [(slot, col), ...] cells awaiting it,
        # with first-registration wall time (the watchdog only nudges
        # dots missing past the pending threshold)
        self._waiters: Dict[int, List[Tuple[int, int]]] = {}
        self._waiter_since: Dict[int, int] = {}
        self._metrics: Metrics = Metrics()
        self._to_execute: Deque[Command] = deque()

    # --- PredecessorsGraph surface ---

    def command_to_execute(self) -> Optional[Command]:
        return self._to_execute.popleft() if self._to_execute else None

    def executed(self) -> AEClock:
        return self._executed_clock.copy()

    def metrics(self) -> Metrics:
        return self._metrics

    @property
    def pending_count(self) -> int:
        """Resident rows still blocked (committed, not yet executed)."""
        return len(self._slot_of)

    def add(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot], time) -> None:
        from fantoch_tpu.executor.pred import PredecessorsExecutionInfo

        self.add_batch([PredecessorsExecutionInfo(dot, cmd, clock, deps)], time)

    def handle_noop(self, dot: Dot, time: SysTime) -> None:
        self.add_batch([], time, noops=[dot])

    def add_batch(self, infos, time, noops=()) -> None:
        """Object-path feed: builds the column batch and funnels through
        the one column path (``add_arrays``)."""
        from fantoch_tpu.executor.pred import PredArraysBuilder

        builder = PredArraysBuilder()
        for dot in noops:
            builder.add_noop(dot)
        for info in infos:
            builder.add_commit(info.dot, info.cmd, info.clock, info.deps)
        batch = builder.take()
        if batch is not None:
            self.add_arrays(batch, time)

    def add_arrays(self, batch, time) -> None:
        """One resident dispatch for a column feed
        (:class:`~fantoch_tpu.executor.pred.PredExecutionArrays`): noop
        resolutions, new committed rows, and the dep patches that wake
        earlier missing-blocked residents."""
        from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL

        clock_seq = np.asarray(batch.clock_seq, dtype=np.int64)
        noop_rows = clock_seq < 0
        live = ~noop_rows
        B = int(live.sum())
        # room FIRST: a mid-feed compaction renumbers slots, and both the
        # noop patches and the install below must see the final numbering
        if B:
            self._make_room(B)
        patches: List[Tuple[int, int, int]] = []
        if noop_rows.any():
            for i in np.flatnonzero(noop_rows).tolist():
                self._note_noop(
                    int(batch.dot_src[i]), int(batch.dot_seq[i]), patches
                )
        if B == 0:
            if patches:
                self._dispatch_columns(
                    np.empty(0, np.int64), np.empty(0, np.int64),
                    np.empty((0, self._width), np.int32), patches, time,
                )
            return

        dot_src = np.asarray(batch.dot_src, dtype=np.int64)[live]
        dot_seq = np.asarray(batch.dot_seq, dtype=np.int64)[live]
        cseq = clock_seq[live]
        csrc = np.asarray(batch.clock_src, dtype=np.int64)[live]
        if len(cseq) and int(cseq.max()) >= _INT32_MAX:
            raise ClockOverflowError(
                "caesar timestamp seq >= 2^31 - 1: the device pred plane "
                "is 31-bit windowed (disable device_pred_plane)"
            )
        if noop_rows.any():
            # re-base dep_row onto the live rows
            row_lut = np.cumsum(live) - 1
            cmds = [c for c, n in zip(batch.cmds, noop_rows) if not n]
        else:
            row_lut = None
            cmds = batch.cmds

        packed = _pack_cols(dot_src, dot_seq)
        packed_list = packed.tolist()
        slot_of = self._slot_of
        exec_recent = self._exec_recent
        mask = (1 << _PACK_SHIFT) - 1
        for pd in packed_list:
            # the executed-clock probe covers dots that executed before
            # the last compaction cleared the recent set — a duplicate
            # commit must trip loudly here like the host twin's
            # committed-clock assert, never re-install and re-execute
            assert (
                pd not in slot_of
                and pd not in exec_recent
                and not self._executed_clock.contains(pd >> _PACK_SHIFT, pd & mask)
            ), "commands are committed exactly once"

        # bump-allocate contiguous slots for the whole feed
        base = self._next_slot
        self._next_slot = base + B
        slots = np.arange(base, base + B, dtype=np.int64)
        slot_of.update(zip(packed_list, range(base, base + B)))
        self._slot_src[base : base + B] = dot_src
        self._slot_seq[base : base + B] = dot_seq
        self._slot_cseq[base : base + B] = cseq
        self._slot_csrc[base : base + B] = csrc
        now = time.millis() if time is not None else 0
        self._slot_start[base : base + B] = now
        self._slot_cmd.update(zip(range(base, base + B), cmds))

        # --- dependency encode (vectorized where it can be) ---
        E = len(batch.dep_row)
        if E:
            dep_row = np.asarray(batch.dep_row, dtype=np.int64)
            if row_lut is not None:
                dep_row = row_lut[dep_row]
            dep_pd = _pack_cols(
                np.asarray(batch.dep_src, np.int64),
                np.asarray(batch.dep_seq, np.int64),
            )
            # self-deps are semantic no-ops (the host twin drops them)
            self_dep = dep_pd == packed[dep_row]
            # one dict/set probe per dependency — the only per-item work
            exec_clock = self._executed_clock
            vals = np.empty(E, dtype=np.int64)
            dep_pd_list = dep_pd.tolist()
            missing_at: List[int] = []
            for e, pd in enumerate(dep_pd_list):
                v = slot_of.get(pd)
                if v is not None:
                    vals[e] = v
                elif pd in exec_recent:
                    vals[e] = TERMINAL
                elif exec_clock.contains(pd >> _PACK_SHIFT, pd & ((1 << _PACK_SHIFT) - 1)):
                    vals[e] = TERMINAL
                else:
                    vals[e] = MISSING
                    missing_at.append(e)
                    self._waiter_since.setdefault(pd, now)
            vals[self_dep] = TERMINAL
            # per-row dep columns: dep_row is emitted row-grouped by the
            # builder, so the column index is the running offset in-group
            iota = np.arange(E, dtype=np.int64)
            head = np.r_[True, dep_row[1:] != dep_row[:-1]]
            col = iota - np.maximum.accumulate(np.where(head, iota, 0))
            width_needed = int(col.max()) + 1 if E else 1
            self._ensure_width(width_needed)
            rows = np.full((B, self._width), TERMINAL, dtype=np.int32)
            rows[dep_row, col] = vals
            # register waiters for the MISSING cells
            for e in missing_at:
                if self_dep[e] or vals[e] != MISSING:
                    continue
                self._waiters.setdefault(dep_pd_list[e], []).append(
                    (int(slots[dep_row[e]]), int(col[e]))
                )
        else:
            rows = np.full((B, self._width), TERMINAL, dtype=np.int32)

        # the residual re-feed: earlier rows waiting on this feed's dots
        if self._waiters:
            for pd, slot in zip(packed_list, range(base, base + B)):
                cells = self._waiters.pop(pd, None)
                if cells is None:
                    continue
                self._waiter_since.pop(pd, None)
                for w_slot, w_col in cells:
                    patches.append((w_slot, w_col, slot))

        self._dispatch_columns(slots, cseq, rows, patches, time, csrc=csrc)

    # --- internals ---

    def _note_noop(self, src: int, seq: int, patches) -> None:
        """A recovery-committed noop: committed AND executed (nothing
        runs), and every cell waiting on it resolves to TERMINAL — a
        command that never existed blocks nobody (the host twin's
        handle_noop)."""
        from fantoch_tpu.ops.graph_resolve import TERMINAL

        pd = _pack(src, seq)
        assert pd not in self._slot_of, "a noop dot has no resident slot"
        added = self._executed_clock.add(src, seq)
        assert added, "commands are committed exactly once"
        self._exec_recent.add(pd)
        self._waiter_since.pop(pd, None)
        for w_slot, w_col in self._waiters.pop(pd, ()):
            patches.append((w_slot, w_col, TERMINAL))

    def _make_room(self, need: int) -> None:
        """Ensure ``need`` contiguous bump slots: grow while the pending
        window could not fit at 3/4 capacity (growing a LIVE window
        recompiles the step program — the 3/4 hysteresis keeps a few
        residual rows from flapping the capacity), then compact the
        window (re-pack pending rows to the bottom — same shape, no
        recompile) when the bump pointer is exhausted anyway."""
        while len(self._slot_of) + need > (3 * self._cap) // 4:
            self._grow_columns()
        if self._next_slot + need > self._cap:
            self._compact()

    def _grow_columns(self) -> None:
        self._grow()  # doubles _cap; re-pads resident state when live
        for name in (
            "_slot_src", "_slot_seq", "_slot_start", "_slot_cseq",
            "_slot_csrc",
        ):
            old = getattr(self, name)
            grown = np.zeros(self._cap, dtype=np.int64)
            grown[: len(old)] = old
            setattr(self, name, grown)

    def _compact(self) -> None:
        """Re-pack the pending window to the bottom of the slot space:
        one state fetch, dep cells remapped through a LUT (references to
        executed rows fold to TERMINAL), one counted re-upload.  Clears
        the recent-executed probe set — those dots are all in the
        executed clock."""
        import jax

        from fantoch_tpu.ops.graph_resolve import TERMINAL

        if self._fault_armed and self._twin_state is not None:
            # the twin is the trusted copy (a resident bit-flip the
            # shadow-check has not sampled yet must never survive a
            # compaction); while failed over it is also the ONLY copy
            self._twin_fold()
            deps = self._twin_state[0]
        else:
            self._materialize()
            # only the dep matrix needs the device round trip: timestamps
            # and occupancy rebuild from the host-mirrored slot columns
            deps = np.asarray(jax.device_get(self._resident[0]))
        old = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        old.sort()  # stable re-pack keeps slot order deterministic
        P = len(old)
        lut = np.full(self._cap, TERMINAL, dtype=np.int32)
        lut[old] = np.arange(P, dtype=np.int32)
        new_deps = deps[old]
        live_cells = new_deps >= 0
        new_deps = np.where(
            live_cells, lut[np.clip(new_deps, 0, self._cap - 1)], new_deps
        )
        state = self._stash_width_cap(self._cap)
        state[0][:P] = new_deps
        state[1][:P] = self._slot_cseq[old]
        state[2][:P] = self._slot_csrc[old]
        state[3][:P] = True  # occ
        # executed stays False: only pending rows survive a compaction
        if self.degraded:
            # no upload while failed over — the compacted window becomes
            # the new twin state; cutback re-uploads it (ONE upload)
            self._twin_resync(tuple(state))
        else:
            self._upload(tuple(state))
            self._host_mirror = None
            self._twin_resync(tuple(state))
        # host columns follow the same re-pack
        self._slot_src[:P] = self._slot_src[old]
        self._slot_seq[:P] = self._slot_seq[old]
        self._slot_start[:P] = self._slot_start[old]
        self._slot_cseq[:P] = self._slot_cseq[old]
        self._slot_csrc[:P] = self._slot_csrc[old]
        # in-place mutation, never rebinding: callers (add_arrays) hold
        # local aliases of these registries across a mid-feed compaction
        cmds = {int(lut[s]): self._slot_cmd[int(s)] for s in old.tolist()}
        self._slot_cmd.clear()
        self._slot_cmd.update(cmds)
        pend_pd = _pack_cols(self._slot_src[:P], self._slot_seq[:P])
        self._slot_of.clear()
        self._slot_of.update(zip(pend_pd.tolist(), range(P)))
        remapped = {
            pd: [(int(lut[s]), c) for s, c in cells]
            for pd, cells in self._waiters.items()
        }
        self._waiters.clear()
        self._waiters.update(remapped)
        self._exec_recent.clear()
        self._next_slot = P
        self.stats["compactions"] += 1

    def _ensure_width(self, width: int) -> None:
        if width <= self._width:
            return
        new_w = _pow2(width)
        if self._fault_armed and self._twin_state is not None:
            # widen from the folded twin (provably clean; the only copy
            # while failed over) — mirrors the base _grow armed path
            self._twin_fold()
            had_resident = self._resident is not None
            self._width = new_w
            self._twin_state = self._pad_state(self._twin_state, self._cap)
            if had_resident:
                self._upload(self._twin_state)
        elif self._resident is not None:
            state = self._fetch_state()
            self._width = new_w
            self._upload(self._pad_state(state, self._cap))
        else:
            self._width = new_w
        self.grows += 1

    # --- host twin (accelerator fault tolerance; DevicePlane base) ---

    def _twin_replay(self, state, entry):
        """One logged window step replayed statelessly: the SAME fused
        kernel over fresh XLA-owned copies of the twin state
        (``jnp.array`` — the donation-safety rule) plus the exact padded
        install/patch columns the resident dispatch consumed.  Outputs
        are the ``newly``-executed mask; the host emission bookkeeping
        (:meth:`_emit`) is shared between device and twin serving, so a
        twin-served dispatch executes bit-for-bit the same commands."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.pred_resolve import resolve_pred_plane_step

        out = resolve_pred_plane_step(
            *(jnp.array(a) for a in state),
            *(jnp.asarray(c) for c in entry),
        )
        fetched = jax.device_get(out)
        return (
            tuple(np.asarray(a) for a in fetched[:5]),
            np.asarray(fetched.newly),
        )

    def _dispatch_columns(self, slots, cseq, rows, patches, time, csrc=None) -> None:
        from fantoch_tpu.ops.graph_resolve import TERMINAL

        U, P = len(slots), len(patches)
        if U == 0 and P == 0:
            if not self.degraded:
                self._materialize()
            return
        # pad the patch columns to a floor so the common serving shapes
        # (a full install batch with zero or a handful of residual
        # patches) all share ONE compiled program — per-dispatch patch
        # counts jitter, and XLA recompiles per distinct shape
        ucap = _pow2(max(U, 1))
        pcap = _pow2(max(P, 64))
        u_row = np.full(ucap, self._cap, dtype=np.int32)  # pad -> dropped
        u_deps = np.full((ucap, self._width), TERMINAL, dtype=np.int32)
        u_clock = np.zeros(ucap, dtype=np.int32)
        u_src = np.zeros(ucap, dtype=np.int32)
        if U:
            u_row[:U] = slots
            u_deps[:U] = rows
            u_clock[:U] = cseq
            u_src[:U] = csrc
        p_row = np.full(pcap, self._cap, dtype=np.int32)  # pad -> dropped
        p_col = np.zeros(pcap, dtype=np.int32)
        p_val = np.zeros(pcap, dtype=np.int32)
        for i, (slot, col, val) in enumerate(patches):
            p_row[i], p_col[i], p_val[i] = slot, col, val

        # the twin logs the exact padded columns BEFORE the dispatch, so
        # a failure mid-dispatch still replays it (armed-only no-op)
        entry = (u_row, u_deps, u_clock, u_src, p_row, p_col, p_val)
        self._twin_note(entry)
        t0 = _time.perf_counter()
        newly = self._serve_step(t0, entry)
        if newly is not None and newly.any():
            self._emit(newly, time)
        self._count_dispatch(
            t0,
            new_rows=U,
            update_capacity=ucap,
            residual_rows=self.pending_count,
        )
        # cutback: once the fault window closed, ONE counted re-upload
        # of the folded twin state (no-op unless failed)
        self._maybe_rebuild()

    def _serve_step(self, t0, entry):
        """One window step under the fault plane: the resident fused
        dispatch when healthy (guarded by the injector, the per-dispatch
        deadline, and the sampled shadow-check), the host twin
        bit-for-bit while failed over.  Returns the ``newly``-executed
        mask consumed by the shared host emission path."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.pred_resolve import resolve_pred_plane_step

        if self.degraded:
            newly = self._twin_fold()
            self._note_degraded(t0)
            return newly
        twin_out = None
        try:
            fault = self._fault_check_pre()
            self._materialize()
            out = resolve_pred_plane_step(
                *self._resident,
                *(jnp.asarray(c) for c in entry),
            )
            self._resident = tuple(out[:5])
            if fault is not None:
                self._poison_resident(fault)
            # one blocking transfer for the dispatch's whole result
            newly = np.asarray(jax.device_get(out.newly))
            self._check_deadline(t0)
            if self._shadow_sampled():
                # the fold's outputs ARE this dispatch's bit-exact twin
                # outputs — kept so a corruption verdict can serve the
                # step without re-replaying
                twin_out = self._twin_fold()
                self._shadow_compare(self._fetch_state())
            return newly
        except (DeviceFailedError, DeviceCorruptionError) as exc:
            # serve THIS step from the twin: the corrupt dispatch's
            # ``newly`` (if any) is discarded before any host bookkeeping
            outputs = twin_out if twin_out is not None else self._twin_fold()
            self._device_failure(exc)
            self._note_degraded(t0)
            return outputs

    def _emit(self, newly: np.ndarray, time) -> None:
        """Vectorized emission of one dispatch's executed slots in
        (clock, src) timestamp order — a host lexsort over the executed
        handful (the slot timestamp columns are host-mirrored); the
        executed clock folds contiguous per-source runs via add_range,
        and the pending registry drops the rows."""
        exec_slots = np.flatnonzero(newly).astype(np.int64)
        exec_slots = exec_slots[
            np.lexsort(
                (self._slot_csrc[exec_slots], self._slot_cseq[exec_slots])
            )
        ]
        srcs = self._slot_src[exec_slots]
        seqs = self._slot_seq[exec_slots]
        cmds = self._slot_cmd
        to_exec = self._to_execute
        slot_of = self._slot_of
        recent = self._exec_recent
        pds = _pack_cols(srcs, seqs).tolist()
        for slot, pd in zip(exec_slots.tolist(), pds):
            to_exec.append(cmds.pop(slot))
            del slot_of[pd]
            recent.add(pd)
        # executed clock: per-source contiguous runs fold to add_range
        sort = np.lexsort((seqs, srcs))
        s_src, s_seq = srcs[sort], seqs[sort]
        run_head = np.r_[
            True, (s_src[1:] != s_src[:-1]) | (s_seq[1:] != s_seq[:-1] + 1)
        ]
        starts = np.flatnonzero(run_head)
        ends = np.r_[starts[1:], len(s_seq)] - 1
        clock = self._executed_clock
        for a, b in zip(starts.tolist(), ends.tolist()):
            clock.add_range(int(s_src[a]), int(s_seq[a]), int(s_seq[b]))
        if time is not None:
            now = time.millis()
            self._metrics.collect_many(
                ExecutorMetricsKind.EXECUTION_DELAY,
                np.maximum(now - self._slot_start[exec_slots], 0),
            )

    # --- liveness watchdog (the PredecessorsGraph contract) ---

    def monitor_pending(self, time: SysTime):
        """Long-pending resident rows are, transitively, blocked on the
        plane's missing frontier (every blocked chain bottoms out at a
        MISSING cell — a fixpoint row with no missing reachable would
        have executed); the frontier IS ``_waiters``' key set, so no walk
        is needed (the host twin memoizes its walk instead).  Only dots
        missing PAST the pending threshold are nudged — the frontier also
        holds dots of healthy in-flight commits, and starting recovery
        consensus against those would preempt live coordinators."""
        from fantoch_tpu.executor.pred import MONITOR_PENDING_THRESHOLD_MS

        fail_ms = self._config.executor_pending_fail_ms
        threshold = (
            MONITOR_PENDING_THRESHOLD_MS
            if fail_ms is None
            else min(MONITOR_PENDING_THRESHOLD_MS, fail_ms)
        )
        now = time.millis()
        mask = (1 << _PACK_SHIFT) - 1
        missing = {
            Dot(pd >> _PACK_SHIFT, pd & mask)
            for pd, since in self._waiter_since.items()
            if now - since >= threshold
        }
        stuck_without_missing: Set[Dot] = set()
        stalled_missing: Dict[Dot, Set[Dot]] = {}
        stalled_for = 0
        all_missing: Set[Dot] = set()
        for pd, slot in self._slot_of.items():
            pending_for = now - int(self._slot_start[slot])
            if pending_for < threshold:
                continue
            dot = Dot(pd >> _PACK_SHIFT, pd & mask)
            if not self._waiters:
                # no missing frontier AT ALL: a long-pending row is a
                # plane bug (every blocked chain bottoms out missing)
                stuck_without_missing.add(dot)
                continue
            if not missing:
                # blocked behind deps whose missing cells are younger
                # than the threshold (a lower-clock late commit's chain):
                # not actionable yet — the frontier matures next ticks
                continue
            all_missing |= missing
            if fail_ms is not None and pending_for >= fail_ms:
                stalled_missing[dot] = missing
                stalled_for = max(stalled_for, pending_for)
        if stuck_without_missing:
            raise AssertionError(
                f"p{self._process_id}: commands pending without missing "
                f"dependencies: {stuck_without_missing}"
            )
        if stalled_missing:
            from fantoch_tpu.errors import StalledExecutionError

            raise StalledExecutionError(
                self._process_id,
                stalled_missing,
                stalled_for,
                self._config.recovery_delay_ms,
            )
        return all_missing

    # --- DevicePlane state hooks ---

    def _fresh_state(self):
        return tuple(self._stash_width_cap(self._cap))

    def _pad_state(self, state, cap: int):
        deps, clock, src, occ, executed = state
        rows = min(len(clock), cap)
        cols = min(deps.shape[1], self._width)
        out = self._stash_width_cap(cap)
        out[0][:rows, :cols] = deps[:rows, :cols]
        out[1][:rows] = clock[:rows]
        out[2][:rows] = src[:rows]
        out[3][:rows] = occ[:rows]
        out[4][:rows] = executed[:rows]
        return tuple(out)

    def _stash_width_cap(self, cap: int):
        from fantoch_tpu.ops.graph_resolve import TERMINAL

        return [
            np.full((cap, self._width), TERMINAL, dtype=np.int32),
            np.zeros(cap, dtype=np.int32),
            np.zeros(cap, dtype=np.int32),
            np.zeros(cap, dtype=bool),
            np.zeros(cap, dtype=bool),
        ]
