from fantoch_tpu.executor.aggregate import AggregatePending
from fantoch_tpu.executor.base import Executor, ExecutorMetricsKind, ExecutorResult, MessageKey
from fantoch_tpu.executor.basic import BasicExecutionInfo, BasicExecutor
from fantoch_tpu.executor.monitor import ExecutionOrderMonitor
from fantoch_tpu.executor.graph.executor import GraphExecutor
