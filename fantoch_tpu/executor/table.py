"""TableExecutor: timestamp-stability ordering for Newt/Tempo.

Reference: fantoch_ps/src/executor/table/{mod,executor}.rs.  Commands carry
a timestamp (clock) and the votes consumed while computing it; a per-key
``VotesTable`` buffers ops sorted by ``(clock, dot)`` and executes every op
whose sort id is below the *stable clock* — the
``(n - stability_threshold)``-th smallest per-process vote frontier, i.e.
the timestamp such that at least ``stability_threshold`` processes have
voted all timestamps up to it, so no new command can be assigned a lower
one (mod.rs:247-270).

Tensor note: per-key frontiers are one ``int32[K, n]`` array on device and
the stable clock one ``jnp.sort`` along the process axis (see
fantoch_tpu/ops); this host twin keys tables lazily for the simulator and
runner control plane.
"""

from __future__ import annotations

from bisect import bisect_left
from collections import deque
from dataclasses import dataclass
from itertools import repeat
from typing import Deque, Dict, List, Optional, Tuple

from fantoch_tpu.core.clocks import RangeEventSet
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, Rifl, ShardId, process_ids
from fantoch_tpu.core.kvs import Key, KVOp, KVOpKind, KVStore
from fantoch_tpu.executor.base import Executor, ExecutorResult
from fantoch_tpu.protocol.common.table_clocks import VoteRange

# ops with equal clocks are tie-broken by dot (mod.rs:18 ``SortId``)
SortId = Tuple[int, Dot]


@dataclass
class TableVotes:
    """TableExecutionInfo::Votes (executor.rs:121-129)."""

    dot: Dot
    clock: int
    rifl: Rifl
    key: Key
    ops: Tuple[KVOp, ...]
    votes: List[VoteRange]


@dataclass
class TableDetachedVotes:
    """TableExecutionInfo::DetachedVotes (executor.rs:130-133)."""

    key: Key
    votes: List[VoteRange]


@dataclass
class TableVotesArrays:
    """Array-borne TableVotes batch (VERDICT r4 #4): B committed rows and
    V vote ranges as columns — the whole proposal->stability->execution
    flow stays in arrays; Rifl/ExecutorResult objects materialize only at
    the client boundary.  Pairs with
    ``BatchedKeyClocks.proposal_batch_arrays``.

    ``vote_row`` ties each vote range to the row whose key it covers
    (coordinator + quorum votes ride with their command, as in MCommit —
    fantoch_ps/src/protocol/newt.rs commit path); detached votes ride the
    optional ``det_*`` columns (one entry per detached vote range,
    ``det_keys`` naming the key directly since there is no row)."""

    keys: List[Key]  # row -> key string
    dot_src: "np.ndarray"  # int64[B]
    dot_seq: "np.ndarray"  # int64[B]
    clock: "np.ndarray"  # int64[B]
    rifl_src: "np.ndarray"  # int64[B]
    rifl_seq: "np.ndarray"  # int64[B]
    ops: List[Tuple[KVOp, ...]]  # row -> command payload
    vote_row: "np.ndarray"  # int64[V] -> row index
    vote_by: "np.ndarray"  # int64[V] process id
    vote_start: "np.ndarray"  # int64[V]
    vote_end: "np.ndarray"  # int64[V]
    det_keys: Optional[List[Key]] = None  # detached vote -> key string
    det_by: Optional["np.ndarray"] = None  # int64[D]
    det_start: Optional["np.ndarray"] = None  # int64[D]
    det_end: Optional["np.ndarray"] = None  # int64[D]


class TableVotesArraysBuilder:
    """Column accumulator for the array-native commit seam: protocols
    (Newt's MCommit path) and the device-plane object converter append
    committed rows / detached votes and flush ONE ``TableVotesArrays``
    per drain — no per-command ``TableVotes`` dataclasses on the batched
    path."""

    __slots__ = (
        "_keys", "_dot_src", "_dot_seq", "_clock", "_rifl_src", "_rifl_seq",
        "_ops", "_vrow", "_vby", "_vstart", "_vend",
        "_dkeys", "_dby", "_dstart", "_dend",
    )

    def __init__(self) -> None:
        self._keys: List[Key] = []
        self._dot_src: List[int] = []
        self._dot_seq: List[int] = []
        self._clock: List[int] = []
        self._rifl_src: List[int] = []
        self._rifl_seq: List[int] = []
        self._ops: List[Tuple[KVOp, ...]] = []
        self._vrow: List[int] = []
        self._vby: List[int] = []
        self._vstart: List[int] = []
        self._vend: List[int] = []
        self._dkeys: List[Key] = []
        self._dby: List[int] = []
        self._dstart: List[int] = []
        self._dend: List[int] = []

    def add_row(
        self,
        dot: Dot,
        clock: int,
        rifl: Rifl,
        key: Key,
        ops: Tuple[KVOp, ...],
        votes,
    ) -> None:
        row = len(self._keys)
        self._keys.append(key)
        self._dot_src.append(dot.source)
        self._dot_seq.append(dot.sequence)
        self._clock.append(clock)
        self._rifl_src.append(rifl.source)
        self._rifl_seq.append(rifl.sequence)
        self._ops.append(ops)
        for vote in votes:
            self._vrow.append(row)
            self._vby.append(vote.by)
            self._vstart.append(vote.start)
            self._vend.append(vote.end)

    def add_detached(self, key: Key, votes) -> None:
        for vote in votes:
            self._dkeys.append(key)
            self._dby.append(vote.by)
            self._dstart.append(vote.start)
            self._dend.append(vote.end)

    def __len__(self) -> int:
        return len(self._keys) + len(self._dkeys)

    def take(self) -> Optional[TableVotesArrays]:
        """Build the accumulated batch and reset; None when empty."""
        import numpy as np

        if not self._keys and not self._dkeys:
            return None
        batch = TableVotesArrays(
            keys=self._keys,
            dot_src=np.asarray(self._dot_src, dtype=np.int64),
            dot_seq=np.asarray(self._dot_seq, dtype=np.int64),
            clock=np.asarray(self._clock, dtype=np.int64),
            rifl_src=np.asarray(self._rifl_src, dtype=np.int64),
            rifl_seq=np.asarray(self._rifl_seq, dtype=np.int64),
            ops=self._ops,
            vote_row=np.asarray(self._vrow, dtype=np.int64),
            vote_by=np.asarray(self._vby, dtype=np.int64),
            vote_start=np.asarray(self._vstart, dtype=np.int64),
            vote_end=np.asarray(self._vend, dtype=np.int64),
            det_keys=self._dkeys or None,
            det_by=np.asarray(self._dby, dtype=np.int64) if self._dkeys else None,
            det_start=(
                np.asarray(self._dstart, dtype=np.int64) if self._dkeys else None
            ),
            det_end=np.asarray(self._dend, dtype=np.int64) if self._dkeys else None,
        )
        self.__init__()
        return batch


TableExecutionInfo = object  # TableVotes | TableDetachedVotes | TableVotesArrays


class VotesTable:
    """Single-key table: vote frontiers per process + clock-sorted op buffer
    (mod.rs:104-270)."""

    __slots__ = ("key", "process_id", "n", "stability_threshold", "_votes", "_ops")

    def __init__(
        self,
        key: Key,
        process_id: ProcessId,
        shard_id: ShardId,
        n: int,
        stability_threshold: int,
    ):
        assert stability_threshold <= n, (
            "stability threshold must always be at most the number of processes"
        )
        self.key = key
        self.process_id = process_id
        self.n = n
        self.stability_threshold = stability_threshold
        self._votes: Dict[ProcessId, RangeEventSet] = {
            pid: RangeEventSet() for pid in process_ids(shard_id, n)
        }
        self._ops: List[Tuple[SortId, Rifl, Tuple[KVOp, ...]]] = []

    def add(
        self,
        dot: Dot,
        clock: int,
        rifl: Rifl,
        ops: Tuple[KVOp, ...],
        votes: List[VoteRange],
    ) -> None:
        self.add_op(dot, clock, rifl, ops)
        self.add_votes(votes)

    def add_op(
        self, dot: Dot, clock: int, rifl: Rifl, ops: Tuple[KVOp, ...]
    ) -> None:
        sort_id = (clock, dot)
        entry = (sort_id, rifl, ops)
        pos = bisect_left(self._ops, entry)
        # duplicate (clock, dot) check in O(log): only a sort_id-equal
        # neighbor could collide
        assert not (
            pos < len(self._ops) and self._ops[pos][0] == sort_id
        ) and not (pos > 0 and self._ops[pos - 1][0] == sort_id), (
            "two commands cannot occupy the same (clock, dot) slot"
        )
        self._ops.insert(pos, entry)

    def add_votes(self, votes: List[VoteRange]) -> None:
        for vote in votes:
            self._votes[vote.by].add_range(vote.start, vote.end)

    def stable_ops(self) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        """Pop every op with sort id strictly below
        ``(stable_clock + 1, first dot)`` — i.e. with clock <= stable_clock
        (mod.rs:200-244; the reference's split_off keeps ops at the bound
        buffered)."""
        return self.stable_ops_at(self.stable_clock())

    def stable_ops_at(
        self, stable_clock: int
    ) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        """stable_ops with a precomputed stable clock (the batched path
        computes all keys' clocks in one kernel and pops per key)."""
        next_stable: SortId = (stable_clock + 1, Dot(1, 1))
        cut = bisect_left(self._ops, (next_stable,))
        stable = [(rifl, ops) for _, rifl, ops in self._ops[:cut]]
        del self._ops[:cut]
        return stable

    def stable_clock(self) -> int:
        """(n - threshold)-th smallest per-process vote frontier
        (mod.rs:247-270)."""
        frontiers = sorted(es.frontier for es in self._votes.values())
        return frontiers[self.n - self.stability_threshold]

    def frontier_row(self) -> List[int]:
        """Per-process vote frontiers in fixed process order (one row of
        the batched ``int32[K, n]`` frontier matrix)."""
        return [es.frontier for es in self._votes.values()]


class MultiVotesTable:
    """Lazily-keyed map of VotesTable (mod.rs:21-102)."""

    __slots__ = ("process_id", "shard_id", "n", "stability_threshold", "_tables")

    def __init__(
        self, process_id: ProcessId, shard_id: ShardId, n: int, stability_threshold: int
    ):
        self.process_id = process_id
        self.shard_id = shard_id
        self.n = n
        self.stability_threshold = stability_threshold
        self._tables: Dict[Key, VotesTable] = {}

    def add_votes(
        self,
        dot: Dot,
        clock: int,
        rifl: Rifl,
        key: Key,
        ops: Tuple[KVOp, ...],
        votes: List[VoteRange],
    ) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        table = self._table(key)
        table.add(dot, clock, rifl, ops, votes)
        return table.stable_ops()

    def add_detached_votes(
        self, key: Key, votes: List[VoteRange]
    ) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        table = self._table(key)
        table.add_votes(votes)
        return table.stable_ops()

    def _table(self, key: Key) -> VotesTable:
        table = self._tables.get(key)
        if table is None:
            table = VotesTable(
                key, self.process_id, self.shard_id, self.n, self.stability_threshold
            )
            self._tables[key] = table
        return table


class TableExecutor(Executor):
    """Executes ops as their timestamps become stable (executor.rs:14-120).

    With ``Config.batched_table_executor`` the per-info stability check is
    replaced by one vectorized pass per batch: votes and ops buffer first,
    then every touched key's stable clock comes out of a single
    ``(n - threshold)``-th order statistic over the frontier matrix — the
    :func:`fantoch_tpu.ops.table_ops.stable_clocks` kernel at device
    scale, a numpy partition below it (identical semantics; kernel
    dispatch only pays off across many keys)."""

    # frontier-matrix element count (keys x n) at which the device kernel
    # beats host numpy: an order statistic over 3-5 columns is a few ns/row
    # on host, so the dispatch only amortizes at millions of elements.
    # Default for Config.table_kernel_threshold = None without an env
    # override (FANTOCH_TABLE_KERNEL_THRESHOLD)
    _KERNEL_THRESHOLD = 1 << 20

    @classmethod
    def _resolve_kernel_threshold(cls, config: Config) -> int:
        from fantoch_tpu.executor.device_plane import resolve_threshold

        return resolve_threshold(
            config.table_kernel_threshold,
            "FANTOCH_TABLE_KERNEL_THRESHOLD",
            cls._KERNEL_THRESHOLD,
        )

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        _, _, stability_threshold = config.newt_quorum_sizes()
        self._process_id = process_id
        self._execute_at_commit = config.execute_at_commit
        # tracing: which batch drain stabilized each traced command
        self._trace_batch = 0
        self._table = MultiVotesTable(process_id, shard_id, config.n, stability_threshold)
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._to_clients: Deque[ExecutorResult] = deque()
        self._batched = config.batched_table_executor
        self._n = config.n
        self._stability_threshold = stability_threshold
        self._kernel_threshold = self._resolve_kernel_threshold(config)
        # device-resident votes-table plane: frontiers live on device
        # across batches; handle/handle_batch/handle_batch_arrays all
        # route through it so the state never forks (executor/table_plane)
        self._plane = None
        if config.device_table_plane:
            from fantoch_tpu.executor.table_plane import DeviceTablePlane
            from fantoch_tpu.ops.pallas_resolve import apply_pallas_config

            # fold Config.pallas_kernels into the kernel route before the
            # plane's first dispatch (config > env > backend default)
            apply_pallas_config(config)
            self._plane = DeviceTablePlane(config.n, stability_threshold)
            # arm the fault plane (deadline + shadow-check) from config;
            # the runners re-seed and attach injectors/listeners on top
            self._plane.configure_faults(config, process_id=process_id)
        # opt-in array drain (the record_order_arrays move from the graph
        # executor): stable rows emit as (rifl_src, rifl_seq) columns and
        # skip KVStore execution + ExecutorResult materialization — for
        # array-native consumers and ordering benches.  Off by default.
        self.record_order_arrays = False
        self._order_arrays: List[Tuple["np.ndarray", "np.ndarray"]] = []

    def _as_arrays_batches(self, infos):
        """Normalize a mixed info stream into TableVotesArrays batches,
        preserving relative order: consecutive object infos merge into one
        batch; pre-built array batches pass through."""
        builder = TableVotesArraysBuilder()
        for info in infos:
            if isinstance(info, TableVotesArrays):
                merged = builder.take()
                if merged is not None:
                    yield merged
                yield info
            elif isinstance(info, TableVotes):
                builder.add_row(
                    info.dot, info.clock, info.rifl, info.key, info.ops,
                    info.votes,
                )
            elif isinstance(info, TableDetachedVotes):
                builder.add_detached(info.key, info.votes)
            else:
                raise AssertionError(f"unknown table execution info {info}")
        merged = builder.take()
        if merged is not None:
            yield merged

    def handle_batch(self, infos, time) -> None:
        self._trace_batch += 1
        if self._plane is not None and not self._execute_at_commit:
            # device plane: every path funnels through the arrays seam so
            # the resident frontier state never forks from a host twin
            for batch in self._as_arrays_batches(infos):
                self.handle_batch_arrays(batch, time)
            return
        if not self._batched or self._execute_at_commit:
            for info in infos:
                self.handle(info, time)
            return
        arrays = [i for i in infos if isinstance(i, TableVotesArrays)]
        if arrays:
            # array batches ride the info stream (Newt's batched commit
            # seam); peel them off for the arrays path
            for batch in arrays:
                self.handle_batch_arrays(batch, time)
            infos = [i for i in infos if not isinstance(i, TableVotesArrays)]
            if not infos:
                return
        # pass 1 (host): buffer ops and *accumulate* votes — per-(key,
        # process) ranges coalesce before touching the RangeEventSets, so
        # a batch of contiguous proposals costs one add_range, not one per
        # command per voter
        touched: Dict[Key, VotesTable] = {}
        acc: Dict[Tuple[Key, ProcessId], List[Tuple[int, int]]] = {}
        for info in infos:
            if isinstance(info, TableVotes):
                table = self._table._table(info.key)
                table.add_op(info.dot, info.clock, info.rifl, info.ops)
                touched[info.key] = table
                for vote in info.votes:
                    acc.setdefault((info.key, vote.by), []).append(
                        (vote.start, vote.end)
                    )
            elif isinstance(info, TableDetachedVotes):
                touched[info.key] = self._table._table(info.key)
                for vote in info.votes:
                    acc.setdefault((info.key, vote.by), []).append(
                        (vote.start, vote.end)
                    )
            else:
                raise AssertionError(f"unknown table execution info {info}")
        for (key, by), ranges in acc.items():
            events = touched[key]._votes[by]
            ranges.sort()
            start, end = ranges[0]
            for nxt_start, nxt_end in ranges[1:]:
                if nxt_start <= end + 1:
                    end = max(end, nxt_end)
                else:
                    events.add_range(start, end)
                    start, end = nxt_start, nxt_end
            events.add_range(start, end)
        if not touched:
            return
        # pass 2 (vectorized): one stability computation over all touched
        # keys (mod.rs:247-270 across the whole batch)
        import numpy as np

        frontiers = np.array(
            [t.frontier_row() for t in touched.values()], dtype=np.int64
        )
        stable = self._stable_clocks(frontiers)
        for (key, table), clock in zip(touched.items(), stable.tolist()):
            ready = table.stable_ops_at(int(clock))
            if ready:
                self._execute(key, ready)

    def handle_batch_arrays(self, batch: TableVotesArrays, time) -> None:
        """The array-native twin of ``handle_batch``: votes coalesce and
        ops order entirely in numpy (or in ONE fused device dispatch when
        the resident plane is on); per-row Python happens only where a
        result object must exist (KVStore execution).  Semantics are
        identical to feeding the equivalent ``TableVotes`` /
        ``TableDetachedVotes`` infos one by one (oracle-equivalence
        tested)."""
        import numpy as np

        self._trace_batch += 1
        B = len(batch.keys)
        det_keys = batch.det_keys or []
        D = len(det_keys)
        if B == 0 and D == 0:
            return
        if self._execute_at_commit:
            if B:
                order = np.lexsort((batch.dot_seq, batch.dot_src, batch.clock))
                for i in order.tolist():
                    self._execute(
                        batch.keys[i],
                        [(Rifl(int(batch.rifl_src[i]), int(batch.rifl_seq[i])),
                          batch.ops[i])],
                    )
            return
        # row + detached keys share one id space.  First-appearance dict
        # factorization: one dict.get per row (~0.3 us) beats np.unique's
        # object-array sort ~6x at 100k rows (measured on this seam)
        index: Dict[Key, int] = {}
        key_list: List[Key] = []
        all_keys = list(batch.keys) + list(det_keys) if D else batch.keys
        key_ids_all = np.empty(B + D, dtype=np.int64)
        for j, k in enumerate(all_keys):
            idx = index.get(k)
            if idx is None:
                idx = len(key_list)
                index[k] = idx
                key_list.append(k)
            key_ids_all[j] = idx
        key_ids = key_ids_all[:B]

        # 1. vote columns: committed rows' votes + detached votes
        V = len(batch.vote_row)
        vkey = key_ids[batch.vote_row] if V else np.empty(0, np.int64)
        vby = np.asarray(batch.vote_by, dtype=np.int64)
        vs = np.asarray(batch.vote_start, dtype=np.int64)
        ve = np.asarray(batch.vote_end, dtype=np.int64)
        if D:
            vkey = np.concatenate([vkey, key_ids_all[B:]])
            vby = np.concatenate([vby, np.asarray(batch.det_by, np.int64)])
            vs = np.concatenate([vs, np.asarray(batch.det_start, np.int64)])
            ve = np.concatenate([ve, np.asarray(batch.det_end, np.int64)])

        # 2. frontier update + stability over all touched keys in one pass:
        # either the resident device plane (one fused dispatch; VotesTable
        # objects materialize lazily, only where an op tail buffers) or
        # the host RangeEventSets + frontier-matrix rebuild
        if self._plane is not None:
            tables = None
            stable = self._plane_stable(key_list, vkey, vby, vs, ve)
        else:
            tables = {k: self._table._table(k) for k in key_list}
            self._coalesce_votes_host(tables, key_list, vkey, vby, vs, ve)
            frontiers = np.array(
                [tables[k].frontier_row() for k in key_list], dtype=np.int64
            )
            stable = self._stable_clocks(frontiers)

        # 3. ops: (key, clock, dot)-sort the batch once; per key segment,
        # the stable prefix executes straight from the columns and only
        # the unstable tail is object-buffered (flow-through batches touch
        # the VotesTable op buffer not at all)
        keys_with_rows = set()
        if B:
            order = np.lexsort(
                (batch.dot_seq, batch.dot_src, batch.clock, key_ids)
            )
            sk = key_ids[order]
            # the object path's add_op asserts (clock, dot) uniqueness per
            # key; the stable prefix below bypasses add_op, so check it
            # here — one vector comparison over the sorted rows
            if len(order) > 1:
                a, b = order[:-1], order[1:]
                dup = (
                    (sk[:-1] == sk[1:])
                    & (batch.clock[a] == batch.clock[b])
                    & (batch.dot_src[a] == batch.dot_src[b])
                    & (batch.dot_seq[a] == batch.dot_seq[b])
                )
                assert not dup.any(), (
                    "two commands cannot occupy the same (clock, dot) slot"
                )
            seg_starts = np.flatnonzero(np.r_[True, sk[1:] != sk[:-1]])
            seg_ends = np.r_[seg_starts[1:], len(order)]
            # python-int columns once per batch: segment emits index into
            # plain lists (C-level int64 -> int conversion, not per-row)
            src_list = batch.rifl_src.tolist()
            seq_list = batch.rifl_seq.tolist()
            ops_all = batch.ops
            for s, e in zip(seg_starts.tolist(), seg_ends.tolist()):
                rows = order[s:e]
                k = int(sk[s])
                keys_with_rows.add(k)
                key = key_list[k]
                table = (
                    tables[key] if tables is not None
                    else self._table._tables.get(key)
                )
                stable_k = int(stable[k])
                if table is not None and table._ops:
                    # rare path: older buffered ops interleave — go through
                    # the object buffer to keep the global (clock, dot) order
                    for i in rows.tolist():
                        table.add_op(
                            Dot(int(batch.dot_src[i]), int(batch.dot_seq[i])),
                            int(batch.clock[i]),
                            Rifl(src_list[i], seq_list[i]),
                            ops_all[i],
                        )
                    ready = table.stable_ops_at(stable_k)
                    if ready:
                        self._execute(key, ready)
                    continue
                cut = int(
                    np.searchsorted(batch.clock[rows], stable_k, side="right")
                )
                if cut:
                    if self.record_order_arrays:
                        sel = rows[:cut]
                        self._order_arrays.append(
                            (batch.rifl_src[sel], batch.rifl_seq[sel])
                        )
                    else:
                        self._emit_stable_rows(
                            key, rows[:cut].tolist(), ops_all,
                            src_list, seq_list,
                        )
                tail = rows[cut:]
                if len(tail):
                    if table is None:  # plane path materializes lazily
                        table = self._table._table(key)
                    for i in tail.tolist():
                        table.add_op(
                            Dot(int(batch.dot_src[i]), int(batch.dot_seq[i])),
                            int(batch.clock[i]),
                            Rifl(src_list[i], seq_list[i]),
                            ops_all[i],
                        )
        # vote-only keys (detached votes, no rows this batch): stability
        # may have advanced past buffered ops — drain them
        for k, key in enumerate(key_list):
            if k in keys_with_rows:
                continue
            table = (
                tables[key] if tables is not None
                else self._table._tables.get(key)
            )
            if table is not None and table._ops:
                ready = table.stable_ops_at(int(stable[k]))
                if ready:
                    self._execute(key, ready)

    def _coalesce_votes_host(
        self, tables, key_list, vkey, vby, vs, ve
    ) -> None:
        """Coalesce vote columns per (key, process) entirely in numpy —
        sort by (key, by, start), compute the per-group running max end
        (groups separated with a large offset so one accumulate serves
        all), and cut merged runs where a start clears the running end by
        > 1.  One add_range call per *merged run* (~ touched keys x
        voters), not per vote row."""
        import numpy as np

        V = len(vkey)
        if not V:
            return
        vorder = np.lexsort((vs, vby, vkey))
        vk = vkey[vorder]
        vb = vby[vorder]
        vs = vs[vorder]
        ve = ve[vorder]
        grp_change = np.r_[True, (vk[1:] != vk[:-1]) | (vb[1:] != vb[:-1])]
        gid = np.cumsum(grp_change) - 1
        base = np.int64(ve.min())
        spread = np.int64(int(ve.max()) - int(base) + 2)
        ngroups = int(gid[-1]) + 1
        if ngroups * int(spread) < (1 << 62):
            # rebase + per-group offset keeps one global accumulate
            # from leaking a group's max end into the next group
            off = gid * spread
            run_end = np.maximum.accumulate((ve - base) + off) - off + base
            prev_end = np.empty_like(run_end)
            prev_end[0] = vs[0]  # dead: grp_change[0] forces a run
            prev_end[1:] = run_end[:-1]
            new_run = grp_change | (vs > prev_end + 1)
            run_starts = np.flatnonzero(new_run)
            m_key = vk[run_starts].tolist()
            m_by = vb[run_starts].tolist()
            m_start = vs[run_starts].tolist()
            m_end = np.maximum.reduceat(ve, run_starts).tolist()
            for k, by, start, end in zip(m_key, m_by, m_start, m_end):
                tables[key_list[k]]._votes[by].add_range(start, end)
        else:
            # pathological clock spread: per-row host merge
            i = 0
            while i < V:
                k, by = int(vk[i]), int(vb[i])
                events = tables[key_list[k]]._votes[by]
                start, end = int(vs[i]), int(ve[i])
                i += 1
                while i < V and vk[i] == k and vb[i] == by:
                    nxt_s, nxt_e = int(vs[i]), int(ve[i])
                    if nxt_s <= end + 1:
                        end = max(end, nxt_e)
                    else:
                        events.add_range(start, end)
                        start, end = nxt_s, nxt_e
                    i += 1
                events.add_range(start, end)

    def _plane_stable(self, key_list, vkey, vby, vs, ve) -> "np.ndarray":
        """Resident-plane stability: ONE fused donated dispatch applies
        the batch's (already key-id'd) vote columns and returns the
        post-batch stable clock per key_list entry."""
        import numpy as np

        plane = self._plane
        buckets = np.fromiter(
            (plane.bucket(k) for k in key_list), np.int64, len(key_list)
        )
        stable_all = plane.commit_votes(
            buckets[vkey] if len(vkey) else np.empty(0, np.int64),
            vby, vs, ve,
        )
        return stable_all[buckets]

    def _stable_clocks(self, frontiers, force_kernel: bool = False) -> "np.ndarray":
        import numpy as np

        k, n = frontiers.shape
        col = n - self._stability_threshold
        if force_kernel or k * n >= self._kernel_threshold:
            base = int(frontiers.min())
            rebased = frontiers - base  # order statistic is shift-invariant
            if int(rebased.max()) < (1 << 31):
                import jax.numpy as jnp

                from fantoch_tpu.ops.table_ops import stable_clocks

                out = stable_clocks(
                    jnp.asarray(rebased.astype(np.int32)),
                    threshold=self._stability_threshold,
                )
                return np.asarray(out).astype(np.int64) + base
        return np.partition(frontiers, col, axis=1)[:, col]

    def handle(self, info, time) -> None:
        if isinstance(info, TableVotesArrays):
            self.handle_batch_arrays(info, time)
            return
        if self._plane is not None and not self._execute_at_commit:
            # the resident plane owns all vote state: single infos route
            # through the arrays seam too
            for batch in self._as_arrays_batches([info]):
                self.handle_batch_arrays(batch, time)
            return
        if isinstance(info, TableVotes):
            if self._execute_at_commit:
                self._execute(info.key, [(info.rifl, info.ops)])
            else:
                ready = self._table.add_votes(
                    info.dot, info.clock, info.rifl, info.key, info.ops, info.votes
                )
                self._execute(info.key, ready)
        elif isinstance(info, TableDetachedVotes):
            if not self._execute_at_commit:
                ready = self._table.add_detached_votes(info.key, info.votes)
                self._execute(info.key, ready)
        else:
            raise AssertionError(f"unknown table execution info {info}")

    def _emit_stable_rows(
        self, key: Key, rows: List[int], ops_all, src_list, seq_list
    ) -> None:
        """Emit a key's stable prefix straight from the batch columns
        (rows already in (clock, dot) order).  The dominant serving shape
        — single-op PUT rows with no execution monitor — applies to the
        KVStore as ONE dict write: each row's result is the previous
        row's value (HashMap::insert semantics, exactly what per-op
        execution returns), so only the Rifl/ExecutorResult constructions
        themselves remain per-row work.  Anything else falls back to
        per-op execution."""
        store = self._store
        if store.monitor is None and store.digest is None:
            # single pass doubles as the fast-path check and the value
            # extraction; bail to per-op execution on the first non-put
            vals = []
            fast = True
            for i in rows:
                ops = ops_all[i]
                if len(ops) == 1 and ops[0].kind is KVOpKind.PUT:
                    vals.append(ops[0].value)
                else:
                    fast = False
                    break
            if fast and vals:
                kv = store._store
                prevs = [kv.get(key)]
                prevs.extend(vals[:-1])  # row i returns row i-1's value
                kv[key] = vals[-1]
                # C-level construction: zip(prevs) yields the 1-tuples,
                # map drives Rifl/ExecutorResult without bytecode per row
                self._to_clients.extend(
                    map(
                        ExecutorResult,
                        map(
                            Rifl,
                            [src_list[i] for i in rows],
                            [seq_list[i] for i in rows],
                        ),
                        repeat(key),
                        zip(prevs),
                    )
                )
                tracer = self.tracer
                if tracer.enabled:
                    for i in rows:
                        rifl = (src_list[i], seq_list[i])
                        tracer.span(
                            "ready", rifl, pid=self._process_id,
                            meta={"batch": self._trace_batch},
                        )
                        tracer.span("executed", rifl, pid=self._process_id)
                return
        self._execute(
            key,
            [(Rifl(src_list[i], seq_list[i]), ops_all[i]) for i in rows],
        )

    def _execute(self, key: Key, to_execute: List[Tuple[Rifl, Tuple[KVOp, ...]]]) -> None:
        if self.record_order_arrays:
            import numpy as np

            m = len(to_execute)
            src = np.fromiter((r.source for r, _ in to_execute), np.int64, m)
            seq = np.fromiter((r.sequence for r, _ in to_execute), np.int64, m)
            self._order_arrays.append((src, seq))
            return
        tracer = self.tracer
        if tracer.enabled:
            # "ready" = the timestamp became stable this batch
            for rifl, _ops in to_execute:
                tracer.span(
                    "ready", rifl, pid=self._process_id,
                    meta={"batch": self._trace_batch},
                )
        store_execute = self._store.execute
        append = self._to_clients.append
        for rifl, ops in to_execute:
            if len(ops) == 1:
                results = (store_execute(key, ops[0], rifl),)
            else:
                results = tuple(store_execute(key, op, rifl) for op in ops)
            append(ExecutorResult(rifl, key, results))
        if tracer.enabled:
            for rifl, _ops in to_execute:
                tracer.span("executed", rifl, pid=self._process_id)

    def device_counters(self):
        """Per-dispatch tallies of the resident votes-table plane (None
        when the plane is off); folded into the run layer's periodic
        metrics snapshot and the bench rows."""
        if self._plane is None:
            return None
        plane = self._plane
        return {
            "table_plane_dispatches": plane.dispatches,
            "table_plane_grows": plane.grows,
            "table_plane_vote_rows": plane.stats["vote_rows"],
            "table_plane_row_capacity": plane.stats["row_capacity"],
            "table_plane_residual_runs": plane.stats["residual_runs"],
            "table_plane_kernel_ms": round(plane.stats["kernel_ms"], 3),
            # host->device frontier materializations: stays at 1 in
            # steady state; restart-from-snapshot costs exactly one more
            "table_plane_resident_uploads": plane.resident_uploads,
            # accelerator fault tolerance: failover/rebuild tallies,
            # degraded wall, and the health gauge (max-folded)
            **{
                f"table_plane_{k}": v
                for k, v in plane.fault_counters().items()
            },
        }

    def device_planes(self):
        return (self._plane,) if self._plane is not None else ()

    def take_order_arrays(self) -> Tuple["np.ndarray", "np.ndarray"]:
        """Concatenated (rifl_src, rifl_seq) execution-order columns since
        the last take; requires ``record_order_arrays`` (same contract as
        BatchedDependencyGraph.take_order_arrays — ordering only, no
        KVStore side effects)."""
        assert self.record_order_arrays
        import numpy as np

        if not self._order_arrays:
            return np.empty(0, np.int64), np.empty(0, np.int64)
        chunks, self._order_arrays = self._order_arrays, []
        if len(chunks) == 1:
            return chunks[0]
        return (
            np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]),
        )

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    def monitor(self):
        return self._store.monitor

    @staticmethod
    def key_of(info) -> Key:
        """MessageKey routing (executor.rs:163-170)."""
        return info.key
