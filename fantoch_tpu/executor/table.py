"""TableExecutor: timestamp-stability ordering for Newt/Tempo.

Reference: fantoch_ps/src/executor/table/{mod,executor}.rs.  Commands carry
a timestamp (clock) and the votes consumed while computing it; a per-key
``VotesTable`` buffers ops sorted by ``(clock, dot)`` and executes every op
whose sort id is below the *stable clock* — the
``(n - stability_threshold)``-th smallest per-process vote frontier, i.e.
the timestamp such that at least ``stability_threshold`` processes have
voted all timestamps up to it, so no new command can be assigned a lower
one (mod.rs:247-270).

Tensor note: per-key frontiers are one ``int32[K, n]`` array on device and
the stable clock one ``jnp.sort`` along the process axis (see
fantoch_tpu/ops); this host twin keys tables lazily for the simulator and
runner control plane.
"""

from __future__ import annotations

from bisect import insort
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from fantoch_tpu.core.clocks import RangeEventSet
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, Rifl, ShardId, process_ids
from fantoch_tpu.core.kvs import Key, KVOp, KVStore
from fantoch_tpu.executor.base import Executor, ExecutorResult
from fantoch_tpu.protocol.common.table_clocks import VoteRange

# ops with equal clocks are tie-broken by dot (mod.rs:18 ``SortId``)
SortId = Tuple[int, Dot]


@dataclass
class TableVotes:
    """TableExecutionInfo::Votes (executor.rs:121-129)."""

    dot: Dot
    clock: int
    rifl: Rifl
    key: Key
    ops: Tuple[KVOp, ...]
    votes: List[VoteRange]


@dataclass
class TableDetachedVotes:
    """TableExecutionInfo::DetachedVotes (executor.rs:130-133)."""

    key: Key
    votes: List[VoteRange]


TableExecutionInfo = object  # TableVotes | TableDetachedVotes


class VotesTable:
    """Single-key table: vote frontiers per process + clock-sorted op buffer
    (mod.rs:104-270)."""

    __slots__ = ("key", "process_id", "n", "stability_threshold", "_votes", "_ops")

    def __init__(
        self,
        key: Key,
        process_id: ProcessId,
        shard_id: ShardId,
        n: int,
        stability_threshold: int,
    ):
        assert stability_threshold <= n, (
            "stability threshold must always be at most the number of processes"
        )
        self.key = key
        self.process_id = process_id
        self.n = n
        self.stability_threshold = stability_threshold
        self._votes: Dict[ProcessId, RangeEventSet] = {
            pid: RangeEventSet() for pid in process_ids(shard_id, n)
        }
        self._ops: List[Tuple[SortId, Rifl, Tuple[KVOp, ...]]] = []

    def add(
        self,
        dot: Dot,
        clock: int,
        rifl: Rifl,
        ops: Tuple[KVOp, ...],
        votes: List[VoteRange],
    ) -> None:
        sort_id = (clock, dot)
        assert all(entry[0] != sort_id for entry in self._ops), (
            "two commands cannot occupy the same (clock, dot) slot"
        )
        insort(self._ops, (sort_id, rifl, ops))
        self.add_votes(votes)

    def add_votes(self, votes: List[VoteRange]) -> None:
        for vote in votes:
            self._votes[vote.by].add_range(vote.start, vote.end)

    def stable_ops(self) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        """Pop every op with sort id strictly below
        ``(stable_clock + 1, first dot)`` — i.e. with clock <= stable_clock
        (mod.rs:200-244; the reference's split_off keeps ops at the bound
        buffered)."""
        from bisect import bisect_left

        stable_clock = self.stable_clock()
        next_stable: SortId = (stable_clock + 1, Dot(1, 1))
        cut = bisect_left(self._ops, (next_stable,))
        stable = [(rifl, ops) for _, rifl, ops in self._ops[:cut]]
        del self._ops[:cut]
        return stable

    def stable_clock(self) -> int:
        """(n - threshold)-th smallest per-process vote frontier
        (mod.rs:247-270)."""
        frontiers = sorted(es.frontier for es in self._votes.values())
        return frontiers[self.n - self.stability_threshold]


class MultiVotesTable:
    """Lazily-keyed map of VotesTable (mod.rs:21-102)."""

    __slots__ = ("process_id", "shard_id", "n", "stability_threshold", "_tables")

    def __init__(
        self, process_id: ProcessId, shard_id: ShardId, n: int, stability_threshold: int
    ):
        self.process_id = process_id
        self.shard_id = shard_id
        self.n = n
        self.stability_threshold = stability_threshold
        self._tables: Dict[Key, VotesTable] = {}

    def add_votes(
        self,
        dot: Dot,
        clock: int,
        rifl: Rifl,
        key: Key,
        ops: Tuple[KVOp, ...],
        votes: List[VoteRange],
    ) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        table = self._table(key)
        table.add(dot, clock, rifl, ops, votes)
        return table.stable_ops()

    def add_detached_votes(
        self, key: Key, votes: List[VoteRange]
    ) -> List[Tuple[Rifl, Tuple[KVOp, ...]]]:
        table = self._table(key)
        table.add_votes(votes)
        return table.stable_ops()

    def _table(self, key: Key) -> VotesTable:
        table = self._tables.get(key)
        if table is None:
            table = VotesTable(
                key, self.process_id, self.shard_id, self.n, self.stability_threshold
            )
            self._tables[key] = table
        return table


class TableExecutor(Executor):
    """Executes ops as their timestamps become stable (executor.rs:14-120)."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        _, _, stability_threshold = config.newt_quorum_sizes()
        self._execute_at_commit = config.execute_at_commit
        self._table = MultiVotesTable(process_id, shard_id, config.n, stability_threshold)
        self._store = KVStore(config.executor_monitor_execution_order)
        self._to_clients: Deque[ExecutorResult] = deque()

    def handle(self, info, time) -> None:
        if isinstance(info, TableVotes):
            if self._execute_at_commit:
                self._execute(info.key, [(info.rifl, info.ops)])
            else:
                ready = self._table.add_votes(
                    info.dot, info.clock, info.rifl, info.key, info.ops, info.votes
                )
                self._execute(info.key, ready)
        elif isinstance(info, TableDetachedVotes):
            if not self._execute_at_commit:
                ready = self._table.add_detached_votes(info.key, info.votes)
                self._execute(info.key, ready)
        else:
            raise AssertionError(f"unknown table execution info {info}")

    def _execute(self, key: Key, to_execute: List[Tuple[Rifl, Tuple[KVOp, ...]]]) -> None:
        for rifl, ops in to_execute:
            results = tuple(self._store.execute(key, op, rifl) for op in ops)
            self._to_clients.append(ExecutorResult(rifl, key, results))

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    def monitor(self):
        return self._store.monitor

    @staticmethod
    def key_of(info) -> Key:
        """MessageKey routing (executor.rs:163-170)."""
        return info.key
