"""PredecessorsExecutor: Caesar's two-phase readiness ordering.

Reference: fantoch_ps/src/executor/pred/{mod,index,executor}.rs.  A
committed command becomes executable in two phases:

* phase 1 — wait until every dependency is *committed* (its final clock is
  known, so the lower-clock comparison below is meaningful);
* phase 2 — wait until every dependency with a *lower clock* is executed.

Timestamps are unique and totally ordered, so unlike the SCC graph executor
there are no cycles to collapse: execution order is exactly increasing
commit timestamp among conflicts.

Tensor note: both phases are countdown counters over a dependency relation
— the device twin is two scatter-add passes over a batched (dot, dep)
edge list (see ops/graph_resolve.py for the shared machinery); this host
implementation drives the simulator and runner control plane.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId, all_process_ids
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import Executor, ExecutorMetricsKind, ExecutorResult
from fantoch_tpu.core.kvs import KVStore
from fantoch_tpu.protocol.common.pred_clocks import Clock


@dataclass
class PredecessorsExecutionInfo:
    dot: Dot
    cmd: Command
    clock: Clock
    deps: Set[Dot]


@dataclass
class PredecessorsNoop:
    """A dot committed as a recovered noop (protocol/recovery.py): nothing
    executes, but dependents waiting on the dot in either phase resolve —
    the Caesar analog of the graph executor's GraphNoop seam."""

    dot: Dot


MONITOR_PENDING_THRESHOLD_MS = 1000


class _Vertex:
    __slots__ = ("dot", "cmd", "clock", "deps", "missing_deps", "start_time_ms")

    def __init__(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot], time: SysTime):
        self.dot = dot
        self.cmd = cmd
        self.clock = clock
        self.deps = deps
        self.missing_deps = 0
        self.start_time_ms = time.millis() if time is not None else 0


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _PendingIndex:
    """dep dot -> dots waiting on it (index.rs PendingIndex)."""

    __slots__ = ("_index",)

    def __init__(self) -> None:
        self._index: Dict[Dot, Set[Dot]] = {}

    def index(self, pending: Dot, dep: Dot) -> None:
        self._index.setdefault(dep, set()).add(pending)

    def remove(self, dep: Dot) -> Set[Dot]:
        return self._index.pop(dep, set())


class PredecessorsGraph:
    def __init__(self, process_id: ProcessId, config: Config):
        ids = [pid for pid, _ in all_process_ids(config.shard_count, config.n)]
        self._process_id = process_id
        self._config = config
        self._committed_clock: AEClock = AEClock(ids)
        self._executed_clock: AEClock = AEClock(ids)
        self._vertices: Dict[Dot, _Vertex] = {}
        self._phase_one_pending = _PendingIndex()
        self._phase_two_pending = _PendingIndex()
        self._metrics: Metrics = Metrics()
        self._to_execute: Deque[Command] = deque()

    def command_to_execute(self) -> Optional[Command]:
        return self._to_execute.popleft() if self._to_execute else None

    def executed(self) -> AEClock:
        return self._executed_clock.copy()

    def metrics(self) -> Metrics:
        return self._metrics

    def add(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot], time: SysTime) -> None:
        # a command may report itself as a dependency (its own clock is in
        # the key index when deps are recomputed); drop it up front
        deps = set(deps)
        deps.discard(dot)

        # index: mark committed, create the vertex
        added = self._committed_clock.add(dot.source, dot.sequence)
        assert added, "commands are committed exactly once"
        assert dot not in self._vertices
        self._vertices[dot] = _Vertex(dot, cmd, clock, deps, time)

        # commands blocked on this dot at phase one may advance
        self._try_phase_one_pending(dot, time)
        self._move_to_phase_one(dot, time)

    def handle_noop(self, dot: Dot, time: SysTime) -> None:
        """A recovery-committed noop: mark the dot committed AND executed
        (nothing runs) and wake everything waiting on it in either phase —
        a phase-two waiter necessarily indexed the dot before it was known
        to be a noop, so both indexes must drain."""
        added = self._committed_clock.add(dot.source, dot.sequence)
        assert added, "commands are committed exactly once"
        added = self._executed_clock.add(dot.source, dot.sequence)
        assert added
        assert dot not in self._vertices, "a noop dot has no vertex"
        self._try_phase_one_pending(dot, time)
        self._try_phase_two_pending(dot, time)

    def monitor_pending(self, time: SysTime):
        """Liveness watchdog (the graph executor's VertexIndex contract):
        log long-pending commands, panic on pending-with-no-missing-deps,
        surface a typed StalledExecutionError when missing dependencies
        stay uncommitted past ``Config.executor_pending_fail_ms``, and
        return the missing dots so the runner can nudge the protocol's
        recovery plane (``Protocol.nudge_recovery``)."""
        fail_ms = self._config.executor_pending_fail_ms
        threshold = (
            MONITOR_PENDING_THRESHOLD_MS
            if fail_ms is None
            else min(MONITOR_PENDING_THRESHOLD_MS, fail_ms)
        )
        now = time.millis()
        stuck_without_missing: Set[Dot] = set()
        stalled_missing: Dict[Dot, Set[Dot]] = {}
        stalled_for = 0
        all_missing: Set[Dot] = set()
        for vertex in self._vertices.values():
            pending_for = now - vertex.start_time_ms
            if pending_for < threshold:
                continue
            missing = self._missing_dependencies(vertex)
            if not missing:
                stuck_without_missing.add(vertex.dot)
            else:
                all_missing |= missing
                if fail_ms is not None and pending_for >= fail_ms:
                    stalled_missing[vertex.dot] = missing
                    stalled_for = max(stalled_for, pending_for)
        if stuck_without_missing:
            raise AssertionError(
                f"p{self._process_id}: commands pending without missing "
                f"dependencies: {stuck_without_missing}"
            )
        if stalled_missing:
            from fantoch_tpu.errors import StalledExecutionError

            raise StalledExecutionError(
                self._process_id,
                stalled_missing,
                stalled_for,
                self._config.recovery_delay_ms,
            )
        return all_missing

    def _missing_dependencies(self, vertex: _Vertex) -> Set[Dot]:
        """Transitively uncommitted dependency dots blocking ``vertex``:
        an uncommitted dep blocks phase one directly; a committed-but-
        unexecuted lower-clock dep blocks phase two through ITS missing
        deps.  Iterative with a visited set — conflict chains under high
        contention fan out, and a naive recursion re-walks shared
        subchains exponentially (fuzzer-found watchdog livelock)."""
        missing: Set[Dot] = set()
        visited: Set[Dot] = {vertex.dot}
        stack = [vertex]
        while stack:
            current = stack.pop()
            for dep in current.deps:
                if dep in visited:
                    continue
                if self._executed_clock.contains(dep.source, dep.sequence):
                    continue
                if not self._committed_clock.contains(dep.source, dep.sequence):
                    missing.add(dep)
                    continue
                visited.add(dep)
                dep_vertex = self._vertices.get(dep)
                if dep_vertex is not None and dep_vertex.clock < current.clock:
                    stack.append(dep_vertex)
        return missing

    def _move_to_phase_one(self, dot: Dot, time: SysTime) -> None:
        vertex = self._vertices[dot]
        non_committed = 0
        for dep in vertex.deps:
            if not self._committed_clock.contains(dep.source, dep.sequence):
                non_committed += 1
                self._phase_one_pending.index(dot, dep)
        if non_committed > 0:
            vertex.missing_deps = non_committed
        else:
            self._move_to_phase_two(dot, time)

    def _move_to_phase_two(self, dot: Dot, time: SysTime) -> None:
        vertex = self._vertices[dot]
        non_executed = 0
        for dep in vertex.deps:
            if not self._executed_clock.contains(dep.source, dep.sequence):
                # all deps are committed by now (phase 1 passed), so the
                # dependency's final clock is known: only lower-clock deps
                # must execute first
                dep_vertex = self._vertices[dep]
                if dep_vertex.clock < vertex.clock:
                    non_executed += 1
                    self._phase_two_pending.index(dot, dep)
        if non_executed > 0:
            vertex.missing_deps = non_executed
        else:
            self._save_to_execute(dot, time)

    def _try_phase_one_pending(self, dot: Dot, time: SysTime) -> None:
        for pending in self._phase_one_pending.remove(dot):
            vertex = self._vertices[pending]
            vertex.missing_deps -= 1
            if vertex.missing_deps == 0:
                self._move_to_phase_two(pending, time)

    def _try_phase_two_pending(self, dot: Dot, time: SysTime) -> None:
        for pending in self._phase_two_pending.remove(dot):
            vertex = self._vertices[pending]
            vertex.missing_deps -= 1
            if vertex.missing_deps == 0:
                self._save_to_execute(pending, time)

    def _save_to_execute(self, dot: Dot, time: SysTime) -> None:
        added = self._executed_clock.add(dot.source, dot.sequence)
        assert added
        vertex = self._vertices.pop(dot)
        if time is not None:
            self._metrics.collect(
                ExecutorMetricsKind.EXECUTION_DELAY,
                time.millis() - vertex.start_time_ms,
            )
        self._to_execute.append(vertex.cmd)
        self._try_phase_two_pending(dot, time)

    # --- the batched seam (ops/pred_resolve.py) ---

    # dep fan-out above this width falls back to the per-info path (the
    # kernel's dep matrix is [B, W]; Caesar deps are lower-clock conflict
    # sets, chain-like under per-key workloads)
    KERNEL_MAX_WIDTH = 32

    def add_batch(self, infos, time: SysTime) -> None:
        """Batched add: one device kernel resolves the whole batch's
        two-phase countdown; only the blocked residue enters the
        per-vertex pending indexes.  Semantics identical to calling
        ``add`` per info (oracle-equivalence tested)."""
        import numpy as np

        from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL
        from fantoch_tpu.ops.pred_resolve import resolve_pred

        infos = [i for i in infos]
        width = max((len(i.deps) for i in infos), default=0)
        if width > self.KERNEL_MAX_WIDTH:
            for info in infos:
                self.add(info.dot, info.cmd, info.clock, info.deps, time)
            return
        B = len(infos)
        if B == 0:
            return
        row_of = {info.dot: r for r, info in enumerate(infos)}
        width = max(width, 1)
        deps = np.full((B, width), TERMINAL, dtype=np.int32)
        for r, info in enumerate(infos):
            s = 0
            for dep in info.deps:
                if dep == info.dot:
                    continue  # self-dependency, dropped like `add` does
                if self._executed_clock.contains(dep.source, dep.sequence):
                    continue  # TERMINAL
                in_batch = row_of.get(dep)
                if in_batch is not None:
                    deps[r, s] = in_batch
                else:
                    # not executed and not in this batch: either entirely
                    # unknown or committed-but-blocked in the host graph —
                    # both block the kernel; the residue path waits on it
                    deps[r, s] = MISSING
                s += 1
        # Caesar clocks are unique (seq, process) pairs: the kernel's
        # (clock, src, seq) lex key carries them exactly.  Pad batch and
        # width to powers of two so XLA compiles O(log) distinct programs
        # as queue-drain sizes vary (the batched.py precedent); pad rows
        # ride the `committed=False` mask and never execute.
        Bp, Wp = _pad_pow2(B), _pad_pow2(width)
        deps_p = np.full((Bp, Wp), TERMINAL, dtype=np.int32)
        deps_p[:B, :width] = deps
        clock = np.zeros(Bp, dtype=np.int32)
        clock[:B] = np.fromiter((i.clock.seq for i in infos), np.int32, B)
        src = np.zeros(Bp, dtype=np.int32)
        src[:B] = np.fromiter((i.clock.process_id for i in infos), np.int32, B)
        seq = np.zeros(Bp, dtype=np.int32)
        committed = np.zeros(Bp, dtype=bool)
        committed[:B] = True
        import jax.numpy as jnp

        res = resolve_pred(
            jnp.asarray(deps_p), jnp.asarray(clock), jnp.asarray(src),
            jnp.asarray(seq), jnp.asarray(committed),
        )
        executed = np.asarray(res.executed)
        order = np.asarray(res.order)
        for r in order.tolist():
            if r >= B or not executed[r]:
                continue
            info = infos[r]
            # the kernel executed it: record commit+execution and wake any
            # host-graph vertices waiting on this dot in either phase
            added = self._committed_clock.add(info.dot.source, info.dot.sequence)
            assert added, "commands are committed exactly once"
            added = self._executed_clock.add(info.dot.source, info.dot.sequence)
            assert added
            if time is not None:
                # same-batch execution: zero delay, but the histogram must
                # count every command the per-info path would count
                self._metrics.collect(ExecutorMetricsKind.EXECUTION_DELAY, 0)
            self._to_execute.append(info.cmd)
            self._try_phase_one_pending(info.dot, time)
            self._try_phase_two_pending(info.dot, time)
        # blocked residue: the ordinary per-vertex path owns it from here
        for r, info in enumerate(infos):
            if not executed[r]:
                self.add(info.dot, info.cmd, info.clock, info.deps, time)


class PredecessorsExecutor(Executor):
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._shard_id = shard_id
        self._execute_at_commit = config.execute_at_commit
        self._batched = config.batched_pred_executor
        self._graph = PredecessorsGraph(process_id, config)
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._to_clients: Deque[ExecutorResult] = deque()

    def handle(self, info, time) -> None:
        if isinstance(info, PredecessorsNoop):
            # execute-at-commit has no ordering state to resolve
            if not self._execute_at_commit:
                self._graph.handle_noop(info.dot, time)
                self._drain()
            return
        if self._execute_at_commit:
            self._execute(info.cmd)
            return
        self._graph.add(info.dot, info.cmd, info.clock, info.deps, time)
        self._drain()

    def handle_batch(self, infos, time) -> None:
        """Batched seam: with ``Config.batched_pred_executor`` the whole
        batch's two-phase countdown resolves as one device kernel
        (ops/pred_resolve.py); otherwise per-info.  Noops take the
        per-info path either way (they carry no clock for the kernel)."""
        if not self._batched or self._execute_at_commit:
            for info in infos:
                self.handle(info, time)
            return
        adds = [i for i in infos if not isinstance(i, PredecessorsNoop)]
        for info in infos:
            if isinstance(info, PredecessorsNoop):
                self._graph.handle_noop(info.dot, time)
        if adds:
            self._graph.add_batch(adds, time)
        self._drain()

    def monitor_pending(self, time):
        """Liveness watchdog; returns the missing dependency dots (if any)
        so the runner can nudge the protocol's recovery plane."""
        if self._execute_at_commit:
            return None
        return self._graph.monitor_pending(time)

    def _drain(self) -> None:
        while True:
            cmd = self._graph.command_to_execute()
            if cmd is None:
                return
            self._execute(cmd)

    def _execute(self, cmd: Command) -> None:
        self._to_clients.extend(cmd.execute(self._shard_id, self._store))

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    def executed(self, time):
        return self._graph.executed()

    @classmethod
    def parallel(cls) -> bool:
        # single process-global dependency graph: key-hash routing cannot
        # split it (the reference marks it parallel only because its infos
        # broadcast to every clone; with one shared graph that is wrong)
        return False

    def metrics(self):
        return self._graph.metrics()

    def monitor(self):
        return self._store.monitor
