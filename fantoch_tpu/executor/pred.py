"""PredecessorsExecutor: Caesar's two-phase readiness ordering.

Reference: fantoch_ps/src/executor/pred/{mod,index,executor}.rs.  A
committed command becomes executable in two phases:

* phase 1 — wait until every dependency is *committed* (its final clock is
  known, so the lower-clock comparison below is meaningful);
* phase 2 — wait until every dependency with a *lower clock* is executed.

Timestamps are unique and totally ordered, so unlike the SCC graph executor
there are no cycles to collapse: execution order is exactly increasing
commit timestamp among conflicts.

Tensor note: both phases are countdown counters over a dependency relation
— the device twin is two scatter-add passes over a batched (dot, dep)
edge list (see ops/graph_resolve.py for the shared machinery); this host
implementation drives the simulator and runner control plane.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId, all_process_ids
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import Executor, ExecutorMetricsKind, ExecutorResult
from fantoch_tpu.core.kvs import KVStore
from fantoch_tpu.protocol.common.pred_clocks import Clock


@dataclass
class PredecessorsExecutionInfo:
    dot: Dot
    cmd: Command
    clock: Clock
    deps: Set[Dot]


@dataclass
class PredecessorsNoop:
    """A dot committed as a recovered noop (protocol/recovery.py): nothing
    executes, but dependents waiting on the dot in either phase resolve —
    the Caesar analog of the graph executor's GraphNoop seam."""

    dot: Dot


@dataclass
class PredExecutionArrays:
    """Column-borne Caesar commit batch (the PR 4 ``TableVotesArrays``
    move): B committed rows and E dependency edges as flat columns, built
    by the protocol's :class:`PredArraysBuilder` and drained ONE batch
    per ``to_executors`` sweep — no per-command
    ``PredecessorsExecutionInfo`` objects on the plane path.  Noop rows
    carry ``clock_seq == -1`` and no payload."""

    dot_src: "np.ndarray"  # int64[B]
    dot_seq: "np.ndarray"  # int64[B]
    clock_seq: "np.ndarray"  # int64[B]; -1 == recovered-noop row
    clock_src: "np.ndarray"  # int64[B]
    cmds: list  # row -> Optional[Command] (None for noop rows)
    dep_row: "np.ndarray"  # int64[E] -> row index
    dep_src: "np.ndarray"  # int64[E]
    dep_seq: "np.ndarray"  # int64[E]


class PredArraysBuilder:
    """Column accumulator for Caesar's commit seam: the protocol appends
    committed ``(dot, cmd, clock, deps)`` rows / recovered noops and
    flushes ONE :class:`PredExecutionArrays` per drain."""

    __slots__ = (
        "_dot_src", "_dot_seq", "_clock_seq", "_clock_src", "_cmds",
        "_dep_row", "_dep_src", "_dep_seq",
    )

    def __init__(self) -> None:
        self._dot_src = []
        self._dot_seq = []
        self._clock_seq = []
        self._clock_src = []
        self._cmds = []
        self._dep_row = []
        self._dep_src = []
        self._dep_seq = []

    def add_commit(self, dot: Dot, cmd: Command, clock, deps) -> None:
        row = len(self._cmds)
        self._dot_src.append(dot.source)
        self._dot_seq.append(dot.sequence)
        self._clock_seq.append(clock.seq)
        self._clock_src.append(clock.process_id)
        self._cmds.append(cmd)
        for dep in deps:
            self._dep_row.append(row)
            self._dep_src.append(dep.source)
            self._dep_seq.append(dep.sequence)

    def add_noop(self, dot: Dot) -> None:
        self._dot_src.append(dot.source)
        self._dot_seq.append(dot.sequence)
        self._clock_seq.append(-1)
        self._clock_src.append(0)
        self._cmds.append(None)

    def __len__(self) -> int:
        return len(self._cmds)

    def take(self) -> Optional[PredExecutionArrays]:
        """Build the accumulated batch and reset; None when empty."""
        import numpy as np

        if not self._cmds:
            return None
        batch = PredExecutionArrays(
            dot_src=np.asarray(self._dot_src, dtype=np.int64),
            dot_seq=np.asarray(self._dot_seq, dtype=np.int64),
            clock_seq=np.asarray(self._clock_seq, dtype=np.int64),
            clock_src=np.asarray(self._clock_src, dtype=np.int64),
            cmds=self._cmds,
            dep_row=np.asarray(self._dep_row, dtype=np.int64),
            dep_src=np.asarray(self._dep_src, dtype=np.int64),
            dep_seq=np.asarray(self._dep_seq, dtype=np.int64),
        )
        self.__init__()
        return batch


def _unpack_arrays(batch: PredExecutionArrays):
    """Expand a column batch back into (infos, noop_dots) — the ONE
    canonical consumption path (host twin and device plane both take
    infos, so the oracle parity argument covers the arrays seam too)."""
    deps_of = [set() for _ in batch.cmds]
    for e in range(len(batch.dep_row)):
        deps_of[int(batch.dep_row[e])].add(
            Dot(int(batch.dep_src[e]), int(batch.dep_seq[e]))
        )
    infos = []
    noops = []
    for i, cmd in enumerate(batch.cmds):
        dot = Dot(int(batch.dot_src[i]), int(batch.dot_seq[i]))
        if int(batch.clock_seq[i]) < 0:
            noops.append(PredecessorsNoop(dot))
        else:
            infos.append(
                PredecessorsExecutionInfo(
                    dot,
                    cmd,
                    Clock(int(batch.clock_seq[i]), int(batch.clock_src[i])),
                    deps_of[i],
                )
            )
    return infos, noops


MONITOR_PENDING_THRESHOLD_MS = 1000


class _Vertex:
    __slots__ = ("dot", "cmd", "clock", "deps", "missing_deps", "start_time_ms")

    def __init__(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot], time: SysTime):
        self.dot = dot
        self.cmd = cmd
        self.clock = clock
        self.deps = deps
        self.missing_deps = 0
        self.start_time_ms = time.millis() if time is not None else 0


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _PendingIndex:
    """dep dot -> dots waiting on it (index.rs PendingIndex)."""

    __slots__ = ("_index",)

    def __init__(self) -> None:
        self._index: Dict[Dot, Set[Dot]] = {}

    def index(self, pending: Dot, dep: Dot) -> None:
        self._index.setdefault(dep, set()).add(pending)

    def remove(self, dep: Dot) -> Set[Dot]:
        return self._index.pop(dep, set())


class PredecessorsGraph:
    def __init__(self, process_id: ProcessId, config: Config):
        ids = [pid for pid, _ in all_process_ids(config.shard_count, config.n)]
        self._process_id = process_id
        self._config = config
        self._committed_clock: AEClock = AEClock(ids)
        self._executed_clock: AEClock = AEClock(ids)
        self._vertices: Dict[Dot, _Vertex] = {}
        self._phase_one_pending = _PendingIndex()
        self._phase_two_pending = _PendingIndex()
        self._metrics: Metrics = Metrics()
        self._to_execute: Deque[Command] = deque()
        # watchdog memo: the transitive-missing map is recomputed only
        # when commit/noop/execution state actually changed since the
        # last tick (the _gen counter) — at 1M pending a re-walk per
        # tick dominated the watchdog; see _missing_map
        self._gen = 0
        self._memo_gen = -1
        self._memo: Dict[Dot, Set[Dot]] = {}

    def command_to_execute(self) -> Optional[Command]:
        return self._to_execute.popleft() if self._to_execute else None

    def executed(self) -> AEClock:
        return self._executed_clock.copy()

    def metrics(self) -> Metrics:
        return self._metrics

    def add(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot], time: SysTime) -> None:
        # a command may report itself as a dependency (its own clock is in
        # the key index when deps are recomputed); drop it up front
        deps = set(deps)
        deps.discard(dot)

        # index: mark committed, create the vertex
        added = self._committed_clock.add(dot.source, dot.sequence)
        assert added, "commands are committed exactly once"
        assert dot not in self._vertices
        self._gen += 1  # commit state changed: watchdog memo stale
        self._vertices[dot] = _Vertex(dot, cmd, clock, deps, time)

        # commands blocked on this dot at phase one may advance
        self._try_phase_one_pending(dot, time)
        self._move_to_phase_one(dot, time)

    def handle_noop(self, dot: Dot, time: SysTime) -> None:
        """A recovery-committed noop: mark the dot committed AND executed
        (nothing runs) and wake everything waiting on it in either phase —
        a phase-two waiter necessarily indexed the dot before it was known
        to be a noop, so both indexes must drain."""
        added = self._committed_clock.add(dot.source, dot.sequence)
        assert added, "commands are committed exactly once"
        added = self._executed_clock.add(dot.source, dot.sequence)
        assert added
        assert dot not in self._vertices, "a noop dot has no vertex"
        self._gen += 1  # commit state changed: watchdog memo stale
        self._try_phase_one_pending(dot, time)
        self._try_phase_two_pending(dot, time)

    def monitor_pending(self, time: SysTime):
        """Liveness watchdog (the graph executor's VertexIndex contract):
        log long-pending commands, panic on pending-with-no-missing-deps,
        surface a typed StalledExecutionError when missing dependencies
        stay uncommitted past ``Config.executor_pending_fail_ms``, and
        return the missing dots so the runner can nudge the protocol's
        recovery plane (``Protocol.nudge_recovery``)."""
        fail_ms = self._config.executor_pending_fail_ms
        threshold = (
            MONITOR_PENDING_THRESHOLD_MS
            if fail_ms is None
            else min(MONITOR_PENDING_THRESHOLD_MS, fail_ms)
        )
        now = time.millis()
        stuck_without_missing: Set[Dot] = set()
        stalled_missing: Dict[Dot, Set[Dot]] = {}
        stalled_for = 0
        all_missing: Set[Dot] = set()
        # lazily built: a healthy tick (no vertex past the threshold)
        # must cost no graph walk at all — the common case in an active
        # system, where commits bump _gen and the memo never carries over
        missing_map = None
        for vertex in self._vertices.values():
            pending_for = now - vertex.start_time_ms
            if pending_for < threshold:
                continue
            if missing_map is None:
                missing_map = self._missing_map()
            missing = missing_map[vertex.dot]
            if not missing:
                stuck_without_missing.add(vertex.dot)
            else:
                all_missing |= missing
                if fail_ms is not None and pending_for >= fail_ms:
                    stalled_missing[vertex.dot] = missing
                    stalled_for = max(stalled_for, pending_for)
        if stuck_without_missing:
            raise AssertionError(
                f"p{self._process_id}: commands pending without missing "
                f"dependencies: {stuck_without_missing}"
            )
        if stalled_missing:
            from fantoch_tpu.errors import StalledExecutionError

            raise StalledExecutionError(
                self._process_id,
                stalled_missing,
                stalled_for,
                self._config.recovery_delay_ms,
            )
        return all_missing

    def _missing_map(self) -> Dict[Dot, Set[Dot]]:
        """Transitively-missing dependency dots per pending vertex: an
        uncommitted dep blocks phase one directly; a committed-but-
        unexecuted lower-clock dep blocks phase two through ITS missing
        deps.  Computed as ONE bottom-up pass over the pending graph
        (blocking chains strictly decrease in clock, so the recursion is
        acyclic and shared subchains are computed once — the naive
        per-vertex re-walk was a fuzzer-found watchdog livelock), and
        MEMOIZED across watchdog ticks: the map only changes when a
        commit/noop/execution lands (``_gen``), so an idle tick at 1M
        pending is a dict read, not a graph walk."""
        if self._memo_gen == self._gen:
            return self._memo
        memo: Dict[Dot, Set[Dot]] = {}
        executed = self._executed_clock
        committed = self._committed_clock
        vertices = self._vertices
        for root in vertices.values():
            if root.dot in memo:
                continue
            # iterative post-order: children (lower-clock pending deps)
            # resolve before their dependents fold them in
            stack = [(root, None)]
            while stack:
                vertex, state = stack.pop()
                if state is None:
                    if vertex.dot in memo:
                        continue
                    missing: Set[Dot] = set()
                    pending_deps = []
                    for dep in vertex.deps:
                        if executed.contains(dep.source, dep.sequence):
                            continue
                        if not committed.contains(dep.source, dep.sequence):
                            missing.add(dep)
                            continue
                        dep_vertex = vertices.get(dep)
                        if dep_vertex is not None and dep_vertex.clock < vertex.clock:
                            pending_deps.append(dep_vertex)
                    stack.append((vertex, (missing, pending_deps)))
                    for dep_vertex in pending_deps:
                        if dep_vertex.dot not in memo:
                            stack.append((dep_vertex, None))
                else:
                    missing, pending_deps = state
                    for dep_vertex in pending_deps:
                        # computed by the post-order (acyclic: clocks
                        # strictly decrease along blocking edges)
                        missing |= memo.get(dep_vertex.dot, set())
                    memo[vertex.dot] = missing
        self._memo = memo
        self._memo_gen = self._gen
        return memo

    def _move_to_phase_one(self, dot: Dot, time: SysTime) -> None:
        vertex = self._vertices[dot]
        non_committed = 0
        for dep in vertex.deps:
            if not self._committed_clock.contains(dep.source, dep.sequence):
                non_committed += 1
                self._phase_one_pending.index(dot, dep)
        if non_committed > 0:
            vertex.missing_deps = non_committed
        else:
            self._move_to_phase_two(dot, time)

    def _move_to_phase_two(self, dot: Dot, time: SysTime) -> None:
        vertex = self._vertices[dot]
        non_executed = 0
        for dep in vertex.deps:
            if not self._executed_clock.contains(dep.source, dep.sequence):
                # all deps are committed by now (phase 1 passed), so the
                # dependency's final clock is known: only lower-clock deps
                # must execute first
                dep_vertex = self._vertices[dep]
                if dep_vertex.clock < vertex.clock:
                    non_executed += 1
                    self._phase_two_pending.index(dot, dep)
        if non_executed > 0:
            vertex.missing_deps = non_executed
        else:
            self._save_to_execute(dot, time)

    def _try_phase_one_pending(self, dot: Dot, time: SysTime) -> None:
        for pending in self._phase_one_pending.remove(dot):
            vertex = self._vertices[pending]
            vertex.missing_deps -= 1
            if vertex.missing_deps == 0:
                self._move_to_phase_two(pending, time)

    def _try_phase_two_pending(self, dot: Dot, time: SysTime) -> None:
        for pending in self._phase_two_pending.remove(dot):
            vertex = self._vertices[pending]
            vertex.missing_deps -= 1
            if vertex.missing_deps == 0:
                self._save_to_execute(pending, time)

    def _save_to_execute(self, dot: Dot, time: SysTime) -> None:
        added = self._executed_clock.add(dot.source, dot.sequence)
        assert added
        self._gen += 1  # execution state changed: watchdog memo stale
        vertex = self._vertices.pop(dot)
        if time is not None:
            self._metrics.collect(
                ExecutorMetricsKind.EXECUTION_DELAY,
                time.millis() - vertex.start_time_ms,
            )
        self._to_execute.append(vertex.cmd)
        self._try_phase_two_pending(dot, time)

    # --- the batched seam (ops/pred_resolve.py) ---

    # dep fan-out above this width falls back to the per-info path (the
    # kernel's dep matrix is [B, W]; Caesar deps are lower-clock conflict
    # sets, chain-like under per-key workloads)
    KERNEL_MAX_WIDTH = 32

    def add_batch(self, infos, time: SysTime) -> None:
        """Batched add: one device kernel resolves the whole batch's
        two-phase countdown; only the blocked residue enters the
        per-vertex pending indexes.  Semantics identical to calling
        ``add`` per info (oracle-equivalence tested)."""
        import numpy as np

        from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL
        from fantoch_tpu.ops.pred_resolve import resolve_pred

        infos = [i for i in infos]
        width = max((len(i.deps) for i in infos), default=0)
        if width > self.KERNEL_MAX_WIDTH:
            for info in infos:
                self.add(info.dot, info.cmd, info.clock, info.deps, time)
            return
        B = len(infos)
        if B == 0:
            return
        row_of = {info.dot: r for r, info in enumerate(infos)}
        width = max(width, 1)
        deps = np.full((B, width), TERMINAL, dtype=np.int32)
        for r, info in enumerate(infos):
            s = 0
            for dep in info.deps:
                if dep == info.dot:
                    continue  # self-dependency, dropped like `add` does
                if self._executed_clock.contains(dep.source, dep.sequence):
                    continue  # TERMINAL
                in_batch = row_of.get(dep)
                if in_batch is not None:
                    deps[r, s] = in_batch
                else:
                    # not executed and not in this batch: either entirely
                    # unknown or committed-but-blocked in the host graph —
                    # both block the kernel; the residue path waits on it
                    deps[r, s] = MISSING
                s += 1
        # Caesar clocks are unique (seq, process) pairs: the kernel's
        # (clock, src, seq) lex key carries them exactly.  Pad batch and
        # width to powers of two so XLA compiles O(log) distinct programs
        # as queue-drain sizes vary (the batched.py precedent); pad rows
        # ride the `committed=False` mask and never execute.
        Bp, Wp = _pad_pow2(B), _pad_pow2(width)
        deps_p = np.full((Bp, Wp), TERMINAL, dtype=np.int32)
        deps_p[:B, :width] = deps
        clock = np.zeros(Bp, dtype=np.int32)
        clock[:B] = np.fromiter((i.clock.seq for i in infos), np.int32, B)
        src = np.zeros(Bp, dtype=np.int32)
        src[:B] = np.fromiter((i.clock.process_id for i in infos), np.int32, B)
        seq = np.zeros(Bp, dtype=np.int32)
        committed = np.zeros(Bp, dtype=bool)
        committed[:B] = True
        import jax.numpy as jnp

        res = resolve_pred(
            jnp.asarray(deps_p), jnp.asarray(clock), jnp.asarray(src),
            jnp.asarray(seq), jnp.asarray(committed),
        )
        executed = np.asarray(res.executed)
        order = np.asarray(res.order)
        for r in order.tolist():
            if r >= B or not executed[r]:
                continue
            info = infos[r]
            # the kernel executed it: record commit+execution and wake any
            # host-graph vertices waiting on this dot in either phase
            added = self._committed_clock.add(info.dot.source, info.dot.sequence)
            assert added, "commands are committed exactly once"
            added = self._executed_clock.add(info.dot.source, info.dot.sequence)
            assert added
            self._gen += 1  # watchdog memo stale
            if time is not None:
                # same-batch execution: zero delay, but the histogram must
                # count every command the per-info path would count
                self._metrics.collect(ExecutorMetricsKind.EXECUTION_DELAY, 0)
            self._to_execute.append(info.cmd)
            self._try_phase_one_pending(info.dot, time)
            self._try_phase_two_pending(info.dot, time)
        # blocked residue: the ordinary per-vertex path owns it from here
        for r, info in enumerate(infos):
            if not executed[r]:
                self.add(info.dot, info.cmd, info.clock, info.deps, time)


class PredecessorsExecutor(Executor):
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._shard_id = shard_id
        self._execute_at_commit = config.execute_at_commit
        self._batched = config.batched_pred_executor
        # device-resident predecessors plane: the whole pending window
        # stays on device across feeds (executor/pred_plane.py); it
        # implements the PredecessorsGraph surface, so everything below
        # drives either twin identically (oracle-parity tested)
        if config.device_pred_plane and not config.execute_at_commit:
            from fantoch_tpu.executor.pred_plane import DevicePredPlane
            from fantoch_tpu.ops.pallas_resolve import apply_pallas_config

            # fold Config.pallas_kernels into the kernel route before the
            # plane's first dispatch (config > env > backend default)
            apply_pallas_config(config)
            self._graph = DevicePredPlane(process_id, config)
            # arm the fault plane (deadline + shadow-check) from config;
            # the runners re-seed and attach injectors/listeners on top
            self._graph.configure_faults(config, process_id=process_id)
        else:
            self._graph = PredecessorsGraph(process_id, config)
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._to_clients: Deque[ExecutorResult] = deque()

    @property
    def _plane(self):
        from fantoch_tpu.executor.pred_plane import DevicePredPlane

        return self._graph if isinstance(self._graph, DevicePredPlane) else None

    def handle(self, info, time) -> None:
        if isinstance(info, PredExecutionArrays):
            self.handle_batch([info], time)
            return
        if isinstance(info, PredecessorsNoop):
            # execute-at-commit has no ordering state to resolve
            if not self._execute_at_commit:
                self._graph.handle_noop(info.dot, time)
                self._drain()
            return
        if self._execute_at_commit:
            self._execute(info.cmd)
            return
        self._graph.add(info.dot, info.cmd, info.clock, info.deps, time)
        self._drain()

    def handle_batch(self, infos, time) -> None:
        """Batched seam: the device pred plane consumes the whole feed
        (adds + noops + any column batches from the protocol's arrays
        builder) as ONE resident dispatch; with
        ``Config.batched_pred_executor`` the batch resolves as one
        upload-per-batch kernel (ops/pred_resolve.resolve_pred);
        otherwise per-info.  Noops take the per-info path on the
        non-plane paths (they carry no clock for the kernel)."""
        plane = None if self._execute_at_commit else self._plane
        if plane is not None:
            # column batches feed the plane natively (no per-command
            # objects); interleaved object infos keep their relative
            # order by flushing as their own column feeds
            adds, noops = [], []

            def _flush_objects():
                if adds or noops:
                    plane.add_batch(adds, time, noops=noops)
                    adds.clear()
                    noops.clear()

            for info in infos:
                if isinstance(info, PredExecutionArrays):
                    _flush_objects()
                    plane.add_arrays(info, time)
                elif isinstance(info, PredecessorsNoop):
                    noops.append(info.dot)
                else:
                    adds.append(info)
            _flush_objects()
            self._drain()
            return
        expanded = []
        for info in infos:
            if isinstance(info, PredExecutionArrays):
                batch_infos, batch_noops = _unpack_arrays(info)
                expanded.extend(batch_infos)
                expanded.extend(batch_noops)
            else:
                expanded.append(info)
        infos = expanded
        if not self._batched or self._execute_at_commit:
            for info in infos:
                self.handle(info, time)
            return
        adds = [i for i in infos if not isinstance(i, PredecessorsNoop)]
        for info in infos:
            if isinstance(info, PredecessorsNoop):
                self._graph.handle_noop(info.dot, time)
        if adds:
            self._graph.add_batch(adds, time)
        self._drain()

    def monitor_pending(self, time):
        """Liveness watchdog; returns the missing dependency dots (if any)
        so the runner can nudge the protocol's recovery plane."""
        if self._execute_at_commit:
            return None
        return self._graph.monitor_pending(time)

    def device_counters(self):
        """Per-dispatch tallies of the resident predecessors plane (None
        when the plane is off); folded into the run layer's periodic
        metrics snapshot and the bench rows — the same
        ``Executor.device_counters`` seam the table plane feeds, so
        ``bin/obs.py summarize`` and the telemetry series cover Caesar
        like Newt."""
        plane = self._plane
        if plane is None:
            return None
        return {
            "pred_plane_dispatches": plane.dispatches,
            "pred_plane_grows": plane.grows,
            "pred_plane_new_rows": plane.stats["new_rows"],
            "pred_plane_update_capacity": plane.stats["update_capacity"],
            "pred_plane_residual_rows": plane.stats["residual_rows"],
            "pred_plane_compactions": plane.stats["compactions"],
            "pred_plane_kernel_ms": round(plane.stats["kernel_ms"], 3),
            # host->device window materializations: 1 lazy initial, +1
            # per compaction / live capacity-or-width grow, +1 per
            # restart-from-snapshot — never one per batch
            "pred_plane_resident_uploads": plane.resident_uploads,
            # configuration gauge (max-folded, not summed)
            "pred_plane_slot_capacity": plane._cap,
            # accelerator fault tolerance: failover/rebuild tallies,
            # degraded wall, and the health gauge (max-folded)
            **{
                f"pred_plane_{k}": v
                for k, v in plane.fault_counters().items()
            },
        }

    def device_planes(self):
        plane = self._plane
        return (plane,) if plane is not None else ()

    def _drain(self) -> None:
        while True:
            cmd = self._graph.command_to_execute()
            if cmd is None:
                return
            self._execute(cmd)

    def _execute(self, cmd: Command) -> None:
        self._to_clients.extend(cmd.execute(self._shard_id, self._store))

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    def executed(self, time):
        return self._graph.executed()

    @classmethod
    def parallel(cls) -> bool:
        # single process-global dependency graph: key-hash routing cannot
        # split it (the reference marks it parallel only because its infos
        # broadcast to every clone; with one shared graph that is wrong)
        return False

    def metrics(self):
        return self._graph.metrics()

    def monitor(self):
        return self._store.monitor
