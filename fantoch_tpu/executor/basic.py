"""Basic executor: executes ops immediately on receipt, key-parallel.

Reference: fantoch/src/executor/basic.rs:12-86.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Optional, Tuple

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ProcessId, Rifl, ShardId
from fantoch_tpu.core.kvs import KVOp, KVStore, Key
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import Executor, ExecutorResult


@dataclass(frozen=True)
class BasicExecutionInfo:
    rifl: Rifl
    key: Key
    ops: Tuple[KVOp, ...]

    @property
    def msg_key(self) -> Key:  # MessageKey routing
        return self.key


class BasicExecutor(Executor):
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._metrics: Metrics = Metrics()
        self._to_clients: deque = deque()

    def handle(self, info: BasicExecutionInfo, time: SysTime) -> None:
        op_results = tuple(self._store.execute(info.key, op, info.rifl) for op in info.ops)
        self._to_clients.append(ExecutorResult(info.rifl, info.key, op_results))

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    def metrics(self) -> Metrics:
        return self._metrics

    def monitor(self):
        return self._store.monitor
