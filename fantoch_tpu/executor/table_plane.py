"""Device-resident votes-table plane for the Newt/Tempo commit path.

The host twin (executor/table.py) keeps one ``RangeEventSet`` per
(key, process) and rebuilds + re-uploads the frontier matrix for every
executor batch — ~68 ms of dispatch round-trip per 71 ms call on the
remote-dispatch rig (BENCH_TPU_LATEST).  This plane applies the move that
won the graph executor: the ``(key_bucket x process)`` frontier matrix
lives ON DEVICE across batches (donated buffers,
``ops/table_ops.fused_votes_commit``), and each batch is one fused
dispatch doing vote-range coalescing (segment-max over sorted
``(key, by)`` runs), frontier update, and stability.

Exactness: a merged vote run that starts beyond a frontier gap cannot
advance the watermark; the kernel marks it *residual* and this class
buffers + re-feeds it with every later batch until the gap fills —
after which the frontier equals what the RangeEventSets would hold
(oracle-equivalence tested, tests/test_table_plane.py).

Clock width: device clocks are int32.  The plane refuses clocks at or
above ``2^31 - 1`` with a typed error instead of silently wrapping —
real-time-micros clock bumps (``Config.newt_clock_bump_interval_ms``)
are rejected at config time (core/config.py).
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from fantoch_tpu.core.kvs import Key
from fantoch_tpu.ops.table_ops import next_pow2 as _pow2

_INT32_MAX = (1 << 31) - 1


class ClockOverflowError(ValueError):
    """A clock or vote endpoint exceeds the plane's 31-bit device window."""



class DeviceTablePlane:
    """Resident vote-frontier state + fused commit dispatch per batch.

    ``commit_votes`` consumes vote columns (already bucketed) and returns
    the post-batch stable clock of every registered bucket; the frontier
    matrix never crosses the host boundary (donated in, donated out).
    """

    __slots__ = (
        "n",
        "threshold",
        "_key_index",
        "_keys",
        "_cap",
        "_frontier",
        "_host_mirror",
        "_res_key",
        "_res_by",
        "_res_start",
        "_res_end",
        "dispatches",
        "grows",
        "resident_uploads",
        "stats",
    )

    def __init__(self, n: int, stability_threshold: int, key_buckets: int = 1024):
        assert stability_threshold <= n
        self.n = n
        self.threshold = stability_threshold
        self._key_index: Dict[Key, int] = {}
        self._keys: List[Key] = []
        self._cap = _pow2(max(key_buckets, 2))
        self._frontier = None  # lazy: created on first dispatch
        # host copy awaiting re-materialization (restart/unpickle path);
        # None while the live matrix is device-resident
        self._host_mirror = None
        empty = np.empty(0, dtype=np.int64)
        self._res_key, self._res_by = empty, empty
        self._res_start, self._res_end = empty, empty
        self.dispatches = 0
        self.grows = 0
        # host->device frontier materializations: 1 for the lazy initial
        # upload, +1 per restore-from-snapshot re-upload (the recovery
        # acceptance signal: restart costs ONE upload, not one per batch)
        self.resident_uploads = 0
        # per-dispatch observability tallies (observability/device.py):
        # vote_rows/row_capacity is the batch occupancy (padding waste),
        # kernel_ms the blocking dispatch+transfer wall time
        self.stats: Dict[str, float] = {
            "vote_rows": 0,
            "row_capacity": 0,
            "residual_runs": 0,
            "kernel_ms": 0.0,
        }

    # --- key registry (string keys -> stable device buckets) ---

    def bucket(self, key: Key) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = len(self._keys)
            self._key_index[key] = idx
            self._keys.append(key)
            if idx >= self._cap:
                self._grow()
        return idx

    @property
    def key_count(self) -> int:
        return len(self._keys)

    def _grow(self) -> None:
        """Double the bucket capacity; pads the resident frontier (one
        host round-trip — rare, amortized by the pow2 schedule)."""
        import jax
        import jax.numpy as jnp

        new_cap = self._cap * 2
        if self._frontier is not None:
            host = np.asarray(jax.device_get(self._frontier))
            padded = np.zeros((new_cap, self.n), dtype=np.int32)
            padded[: self._cap] = host
            # jnp.array copies into an XLA-owned buffer: jnp.asarray
            # would zero-copy alias ``padded``'s numpy memory on CPU, and
            # fused_votes_commit donates this buffer (use-after-free)
            self._frontier = jnp.array(padded)
            self.resident_uploads += 1
        self._cap = new_cap
        self.grows += 1

    def _materialize(self) -> None:
        """Ensure the frontier matrix is device-resident: lazy initial
        creation, or the ONE re-upload from the host mirror after
        restore-from-snapshot (the restart plane's lazy
        re-materialization seam — same discipline as
        ``BatchedKeyClocks``)."""
        if self._frontier is not None:
            return
        import jax
        import jax.numpy as jnp

        if self._host_mirror is not None:
            padded = np.zeros((self._cap, self.n), dtype=np.int32)
            rows = min(len(self._host_mirror), self._cap)
            padded[:rows] = self._host_mirror[:rows]
            # jnp.array: XLA-owned copy (the donation-safety rule)
            self._frontier = jnp.array(padded)
            self._host_mirror = None
        else:
            self._frontier = jax.device_put(
                jnp.zeros((self._cap, self.n), dtype=jnp.int32)
            )
        self.resident_uploads += 1

    # --- durability (Executor.snapshot pickles through here) ---

    def __getstate__(self):
        state = {
            slot: getattr(self, slot)
            for slot in self.__slots__
            if slot not in ("_frontier", "_host_mirror")
        }
        host = self._host_mirror
        if self._frontier is not None:
            import jax

            host = np.asarray(jax.device_get(self._frontier)).astype(np.int32)
        state["_host_mirror"] = host
        return state

    def __setstate__(self, state) -> None:
        for slot, value in state.items():
            setattr(self, slot, value)
        # device state never survives a pickle: the next dispatch
        # re-materializes from the host mirror (ONE counted upload)
        self._frontier = None

    # --- the fused commit dispatch ---

    def commit_votes(
        self,
        vkey: np.ndarray,  # int64[V] bucket ids (from ``bucket``)
        vby: np.ndarray,  # int64[V] process ids, 1-based (protocol ids)
        vstart: np.ndarray,  # int64[V]
        vend: np.ndarray,  # int64[V]
    ) -> np.ndarray:
        """Apply a batch of vote ranges; returns ``int64[key_count]``
        stable clocks (post-batch) for every registered bucket.  Residual
        (beyond-gap) runs are buffered internally and re-fed with the
        next batch."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import fused_votes_commit

        if len(vend) and int(np.max(vend)) >= _INT32_MAX:
            raise ClockOverflowError(
                "vote endpoint >= 2^31 - 1: the device table plane is "
                "31-bit windowed (disable device_table_plane for "
                "real-time-micros clocks)"
            )
        # prepend buffered residuals so gap-filling batches coalesce with
        # the runs they unblock
        vkey = np.concatenate([self._res_key, vkey])
        vby = np.concatenate([self._res_by, vby])
        vstart = np.concatenate([self._res_start, vstart])
        vend = np.concatenate([self._res_end, vend])
        V = len(vkey)

        self._materialize()
        if V == 0:
            # nothing to apply: stability unchanged — read it off the
            # resident state with the plain (non-donating) kernel
            from fantoch_tpu.ops.table_ops import stable_clocks

            stable = stable_clocks(self._frontier, threshold=self.threshold)
            return np.asarray(jax.device_get(stable)).astype(np.int64)[
                : self.key_count
            ]

        # pad the vote columns to pow2 so XLA compiles O(log) programs
        vcap = _pow2(V)
        pk = np.zeros(vcap, dtype=np.int32)
        pb = np.zeros(vcap, dtype=np.int32)
        ps = np.zeros(vcap, dtype=np.int32)
        pe = np.zeros(vcap, dtype=np.int32)
        pk[:V] = vkey
        pb[:V] = vby - 1  # protocol process ids are 1-based; columns 0-based
        ps[:V] = vstart
        pe[:V] = vend
        pvalid = np.zeros(vcap, dtype=bool)
        pvalid[:V] = True

        t0 = time.perf_counter()
        out = fused_votes_commit(
            self._frontier,
            jnp.asarray(pk),
            jnp.asarray(pb),
            jnp.asarray(ps),
            jnp.asarray(pe),
            jnp.asarray(pvalid),
            threshold=self.threshold,
        )
        self._frontier = out[0]
        # one blocking transfer for stability + the residual run columns
        stable, run_key, run_by, run_start, run_end, residual = jax.device_get(
            out[1:]
        )
        self.dispatches += 1
        stats = self.stats
        stats["kernel_ms"] += (time.perf_counter() - t0) * 1000.0
        stats["vote_rows"] += V
        stats["row_capacity"] += vcap
        res = np.flatnonzero(residual)
        stats["residual_runs"] += len(res)
        self._res_key = run_key[res].astype(np.int64)
        self._res_by = (run_by[res] + 1).astype(np.int64)  # back to 1-based
        self._res_start = run_start[res].astype(np.int64)
        self._res_end = run_end[res].astype(np.int64)
        return stable.astype(np.int64)[: self.key_count]

    # --- introspection (tests / debugging) ---

    def frontiers(self) -> np.ndarray:
        """Host copy of the live ``int64[key_count, n]`` frontier matrix
        (a device round-trip; for tests and debugging only)."""
        import jax

        if self._frontier is None:
            if self._host_mirror is not None:
                return self._host_mirror[: self.key_count].astype(np.int64)
            return np.zeros((self.key_count, self.n), dtype=np.int64)
        host = np.asarray(jax.device_get(self._frontier)).astype(np.int64)
        return host[: self.key_count]

    @property
    def residual_count(self) -> int:
        return len(self._res_key)
