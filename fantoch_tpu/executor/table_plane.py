"""Device-resident votes-table plane for the Newt/Tempo commit path.

The host twin (executor/table.py) keeps one ``RangeEventSet`` per
(key, process) and rebuilds + re-uploads the frontier matrix for every
executor batch — ~68 ms of dispatch round-trip per 71 ms call on the
remote-dispatch rig (BENCH_TPU_LATEST).  This plane applies the move that
won the graph executor: the ``(key_bucket x process)`` frontier matrix
lives ON DEVICE across batches (donated buffers,
``ops/table_ops.fused_votes_commit``), and each batch is one fused
dispatch doing vote-range coalescing (segment-max over sorted
``(key, by)`` runs), frontier update, and stability.

Exactness: a merged vote run that starts beyond a frontier gap cannot
advance the watermark; the kernel marks it *residual* and this class
buffers + re-feeds it with every later batch until the gap fills —
after which the frontier equals what the RangeEventSets would hold
(oracle-equivalence tested, tests/test_table_plane.py).

Buffer lifecycle (donation safety, lazy host-mirror re-materialization
with the single counted re-upload, pow2 growth, per-dispatch counters)
comes from the shared :class:`~fantoch_tpu.executor.device_plane.DevicePlane`
base — the same machinery the Caesar predecessors plane
(executor/pred_plane.py) rides.

Clock width: device clocks are int32.  The plane refuses clocks at or
above ``2^31 - 1`` with a typed error instead of silently wrapping —
real-time-micros clock bumps (``Config.newt_clock_bump_interval_ms``)
are rejected at config time (core/config.py).
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

from fantoch_tpu.errors import DeviceCorruptionError, DeviceFailedError
from fantoch_tpu.executor.device_plane import DevicePlane, next_pow2 as _pow2

_INT32_MAX = (1 << 31) - 1


class ClockOverflowError(ValueError):
    """A clock or vote endpoint exceeds the plane's 31-bit device window."""



class DeviceTablePlane(DevicePlane):
    """Resident vote-frontier state + fused commit dispatch per batch.

    ``commit_votes`` consumes vote columns (already bucketed) and returns
    the post-batch stable clock of every registered bucket; the frontier
    matrix never crosses the host boundary (donated in, donated out).
    """

    __slots__ = ("n", "threshold")

    plane_name = "table"

    def __init__(self, n: int, stability_threshold: int, key_buckets: int = 1024):
        assert stability_threshold <= n
        super().__init__(
            key_buckets,
            stats={
                # per-dispatch observability tallies (observability/
                # device.py): vote_rows/row_capacity is the batch
                # occupancy (padding waste), kernel_ms the blocking
                # dispatch+transfer wall time
                "vote_rows": 0,
                "row_capacity": 0,
                "residual_runs": 0,
                "kernel_ms": 0.0,
            },
        )
        self.n = n
        self.threshold = stability_threshold

    # --- DevicePlane state hooks (state = the 1-tuple frontier matrix) ---

    def _fresh_state(self) -> Tuple[np.ndarray, ...]:
        return (np.zeros((self._cap, self.n), dtype=np.int32),)

    def _pad_state(self, state, cap: int) -> Tuple[np.ndarray, ...]:
        (host,) = state
        padded = np.zeros((cap, self.n), dtype=np.int32)
        rows = min(len(host), cap)
        padded[:rows] = host[:rows]
        return (padded,)

    @property
    def _frontier(self):
        return self._resident[0] if self._resident is not None else None

    # --- host twin (accelerator fault tolerance; DevicePlane base) ---

    def _twin_replay(self, state, entry):
        """One logged commit dispatch replayed statelessly: the SAME
        fused kernel over a fresh XLA-owned copy of the twin frontier
        (``jnp.array`` — the donation-safety rule) plus the exact padded
        columns the resident dispatch consumed — outputs are bit-for-bit
        what a healthy device produced/would have produced."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import fused_votes_commit

        pk, pb, ps, pe, pvalid = entry
        (frontier,) = state
        out = fused_votes_commit(
            jnp.array(frontier),
            jnp.asarray(pk),
            jnp.asarray(pb),
            jnp.asarray(ps),
            jnp.asarray(pe),
            jnp.asarray(pvalid),
            threshold=self.threshold,
        )
        fetched = jax.device_get(out)
        return (np.asarray(fetched[0]),), tuple(
            np.asarray(a) for a in fetched[1:]
        )

    # --- the fused commit dispatch ---

    def commit_votes(
        self,
        vkey: np.ndarray,  # int64[V] bucket ids (from ``bucket``)
        vby: np.ndarray,  # int64[V] process ids, 1-based (protocol ids)
        vstart: np.ndarray,  # int64[V]
        vend: np.ndarray,  # int64[V]
    ) -> np.ndarray:
        """Apply a batch of vote ranges; returns ``int64[key_count]``
        stable clocks (post-batch) for every registered bucket.  Residual
        (beyond-gap) runs are buffered internally and re-fed with the
        next batch."""
        if len(vend) and int(np.max(vend)) >= _INT32_MAX:
            raise ClockOverflowError(
                "vote endpoint >= 2^31 - 1: the device table plane is "
                "31-bit windowed (disable device_table_plane for "
                "real-time-micros clocks)"
            )
        # prepend buffered residuals so gap-filling batches coalesce with
        # the runs they unblock
        vkey, vby, vstart, vend = self._take_residuals(
            (vkey, vby, vstart, vend)
        )
        V = len(vkey)

        if V == 0:
            return self._stable_only()

        # pad the vote columns to pow2 so XLA compiles O(log) programs
        vcap = _pow2(V)
        pk = np.zeros(vcap, dtype=np.int32)
        pb = np.zeros(vcap, dtype=np.int32)
        ps = np.zeros(vcap, dtype=np.int32)
        pe = np.zeros(vcap, dtype=np.int32)
        pk[:V] = vkey
        pb[:V] = vby - 1  # protocol process ids are 1-based; columns 0-based
        ps[:V] = vstart
        pe[:V] = vend
        pvalid = np.zeros(vcap, dtype=bool)
        pvalid[:V] = True

        # the twin logs the exact padded columns BEFORE the dispatch, so
        # a failure mid-dispatch still replays it (armed-only no-op)
        self._twin_note((pk, pb, ps, pe, pvalid))
        t0 = time.perf_counter()
        stable, run_key, run_by, run_start, run_end, residual = (
            self._serve_commit(t0, pk, pb, ps, pe, pvalid)
        )
        res = np.flatnonzero(residual)
        self._count_dispatch(
            t0, vote_rows=V, row_capacity=vcap, residual_runs=len(res)
        )
        self._put_residuals(
            (
                run_key[res].astype(np.int64),
                (run_by[res] + 1).astype(np.int64),  # back to 1-based
                run_start[res].astype(np.int64),
                run_end[res].astype(np.int64),
            )
        )
        # cutback: once the fault window closed, ONE counted re-upload
        # of the folded twin state (no-op unless failed)
        self._maybe_rebuild()
        return stable.astype(np.int64)[: self.key_count]

    def _serve_commit(self, t0, pk, pb, ps, pe, pvalid):
        """One commit dispatch under the fault plane: the resident fused
        dispatch when healthy (guarded by the injector, the per-dispatch
        deadline, and the sampled shadow-check), the host twin bit-for-bit
        while failed over."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import fused_votes_commit

        if self.degraded:
            outputs = self._twin_fold()
            self._note_degraded(t0)
            return outputs
        twin_out = None
        try:
            fault = self._fault_check_pre()
            self._materialize()
            out = fused_votes_commit(
                self._frontier,
                jnp.asarray(pk),
                jnp.asarray(pb),
                jnp.asarray(ps),
                jnp.asarray(pe),
                jnp.asarray(pvalid),
                threshold=self.threshold,
            )
            self._resident = (out[0],)
            if fault is not None:
                self._poison_resident(fault)
            # one blocking transfer for stability + the residual columns
            fetched = jax.device_get(out[1:])
            self._check_deadline(t0)
            if self._shadow_sampled():
                # the fold's outputs ARE this dispatch's bit-exact twin
                # outputs — kept so a corruption verdict can serve the
                # batch without re-replaying
                twin_out = self._twin_fold()
                self._shadow_compare(self._fetch_state())
            return tuple(np.asarray(a) for a in fetched)
        except (DeviceFailedError, DeviceCorruptionError) as exc:
            # serve THIS batch from the twin: either the shadow fold
            # above already produced its outputs, or the log still holds
            # the entry and one fold replays it
            outputs = twin_out if twin_out is not None else self._twin_fold()
            self._device_failure(exc)
            self._note_degraded(t0)
            return outputs

    def _stable_only(self):
        """The V == 0 path: stability unchanged — read it off the
        resident state (or the twin while failed over) with the plain
        non-donating kernel."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import stable_clocks

        if self.degraded:
            t0 = time.perf_counter()
            self._twin_fold()
            stable = stable_clocks(
                jnp.asarray(self._twin_state[0]), threshold=self.threshold
            )
            result = np.asarray(jax.device_get(stable)).astype(np.int64)[
                : self.key_count
            ]
            self._note_degraded(t0)
            self._maybe_rebuild()
            return result
        self._materialize()
        stable = stable_clocks(self._frontier, threshold=self.threshold)
        return np.asarray(jax.device_get(stable)).astype(np.int64)[
            : self.key_count
        ]

    # --- introspection (tests / debugging) ---

    def frontiers(self) -> np.ndarray:
        """Host copy of the live ``int64[key_count, n]`` frontier matrix
        (a device round-trip; for tests and debugging only)."""
        if self._resident is None:
            if self.degraded and self._twin_state is not None:
                self._twin_fold()
                return self._twin_state[0][: self.key_count].astype(np.int64)
            if self._host_mirror is not None:
                return self._host_mirror[0][: self.key_count].astype(np.int64)
            return np.zeros((self.key_count, self.n), dtype=np.int64)
        return self._fetch_state()[0].astype(np.int64)[: self.key_count]
