"""Aggregation of per-key executor results into full command results.

Reference: fantoch/src/executor/aggregate.rs:9-98.  The server side of the
client plane: a command touching k keys produces k partial results (possibly
from different key-parallel executors); the pending tracker releases one
``CommandResult`` once all partials arrive.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.ids import ProcessId, Rifl, ShardId
from fantoch_tpu.executor.base import ExecutorResult
from fantoch_tpu.utils import logger


class AggregatePending:
    """``buffer_early``: stash partials whose ``wait_for`` has not arrived
    yet instead of dropping them.  The runner's per-client session needs
    this (results are routed to the session by owning client id, so every
    partial seen here belongs to one of its clients): on a NON-target
    shard of a multi-shard command, the server-side MForwardSubmit can
    commit and execute before the client's own Register message arrives
    over its connection, and dropping that early partial deadlocks the
    client.  The simulator/test drivers keep the default drop behavior —
    there, every process executes every command including those of clients
    attached elsewhere, and foreign partials must be ignored, not held.
    """

    # bound on buffered early *partials* (total across rifls): a rifl
    # whose Register/Submit never arrives (client died after ClientHi, or
    # a stream of misrouted results for one dead rifl) must not leak for
    # the life of the session.  Oldest-rifl eviction; the cap is
    # per-session so a small bound suffices.
    EARLY_CAP = 1024

    def __init__(
        self, process_id: ProcessId, shard_id: ShardId, buffer_early: bool = False
    ):
        self._process_id = process_id
        self._shard_id = shard_id
        self._pending: Dict[Rifl, CommandResult] = {}
        self._buffer_early = buffer_early
        self._early: Dict[Rifl, List[ExecutorResult]] = {}
        self._early_count = 0

    def wait_for(self, cmd: Command) -> bool:
        """Track a command submitted by a connected client."""
        rifl = cmd.rifl
        key_count = cmd.key_count(self._shard_id)
        existed = rifl in self._pending
        self._pending[rifl] = CommandResult(rifl, key_count)
        return not existed

    def wait_for_rifl(self, rifl: Rifl) -> None:
        """Increase expected partials for `rifl` by one (used by executors
        that produce one notification per key without seeing the command)."""
        result = self._pending.get(rifl)
        if result is None:
            result = CommandResult(rifl, 0)
            self._pending[rifl] = result
        result.increment_key_count()

    def cancel(self, rifl: Rifl) -> None:
        """Withdraw a tracked command (the overload plane's deadline-shed
        path: the client will never resubmit, so the aggregation entry —
        and any buffered early partials — must not outlive it)."""
        self._pending.pop(rifl, None)
        dropped = self._early.pop(rifl, None)
        if dropped:
            self._early_count -= len(dropped)

    def drain_early(self, rifl: Rifl) -> Optional[CommandResult]:
        """Apply partials that raced ahead of ``wait_for(rifl)``; returns
        the CommandResult if they already complete it."""
        partials = self._early.pop(rifl, [])
        self._early_count -= len(partials)
        for partial in partials:
            done = self.add_executor_result(partial)
            if done is not None:
                return done
        return None

    def add_executor_result(self, executor_result: ExecutorResult) -> Optional[CommandResult]:
        """Add one partial; returns the CommandResult once complete.
        Partials for unknown rifls are buffered (``buffer_early``) or
        ignored (clients of other processes)."""
        cmd_result = self._pending.get(executor_result.rifl)
        if cmd_result is None:
            if self._buffer_early:
                self._early.setdefault(executor_result.rifl, []).append(
                    executor_result
                )
                self._early_count += 1
                while self._early_count > self.EARLY_CAP:
                    # dicts iterate in insertion order: drop the oldest rifl
                    evicted = next(iter(self._early))
                    self._early_count -= len(self._early.pop(evicted))
                    # if the evicted rifl's wait_for was merely racing (not
                    # dead), its command will now hang silently — leave a
                    # trail so a wedged client is diagnosable
                    logger.warning(
                        "early-partial cap: evicting partials for rifl %s",
                        evicted,
                    )
            return None
        if cmd_result.add_partial(executor_result.key, executor_result.op_results):
            return self._pending.pop(executor_result.rifl)
        return None
