"""Execution-order monitor: records per-key execution order so tests can
assert that all processes agree (the linearizable-agreement check).

Reference: fantoch/src/executor/monitor.rs:8-58.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import Key


class ExecutionOrderMonitor:
    def __init__(self) -> None:
        self._order_per_key: Dict[Key, List[Rifl]] = {}

    def add(self, key: Key, rifl: Rifl) -> None:
        self._order_per_key.setdefault(key, []).append(rifl)

    def merge(self, other: "ExecutionOrderMonitor") -> None:
        """Merge a disjoint-key monitor (multiple key-parallel executors)."""
        for key, rifls in other._order_per_key.items():
            assert key not in self._order_per_key, (
                "different monitors should operate on different keys"
            )
            self._order_per_key[key] = rifls

    def get_order(self, key: Key) -> Optional[List[Rifl]]:
        return self._order_per_key.get(key)

    def keys(self) -> Iterator[Key]:
        return iter(self._order_per_key.keys())

    def __len__(self) -> int:
        return len(self._order_per_key)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ExecutionOrderMonitor)
            and self._order_per_key == other._order_per_key
        )

    def __repr__(self) -> str:
        return f"ExecutionOrderMonitor({self._order_per_key})"
