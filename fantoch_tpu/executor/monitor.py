"""Execution-order monitor: records per-key execution order so tests can
assert that all processes agree (the linearizable-agreement check).

Reference: fantoch/src/executor/monitor.rs:8-58.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import Key


class ExecutionOrderMonitor:
    def __init__(self) -> None:
        self._order_per_key: Dict[Key, List[Rifl]] = {}
        # (key, rifl) pairs recorded as reads: with the KeyDeps read/write
        # split (graph_deps.py), reads commute and their relative order is
        # legitimately unordered — agreement checks compare write orders.
        # Keyed per (key, rifl), not rifl: a mixed command could read one
        # key and write another, and its writes must stay in the check.
        self._reads: set = set()

    def add(self, key: Key, rifl: Rifl, read: bool = False) -> None:
        self._order_per_key.setdefault(key, []).append(rifl)
        if read:
            self._reads.add((key, rifl))

    def merge(self, other: "ExecutionOrderMonitor") -> None:
        """Merge a disjoint-key monitor (multiple key-parallel executors)."""
        for key, rifls in other._order_per_key.items():
            assert key not in self._order_per_key, (
                "different monitors should operate on different keys"
            )
            self._order_per_key[key] = rifls
        self._reads |= other._reads

    def get_order(self, key: Key) -> Optional[List[Rifl]]:
        return self._order_per_key.get(key)

    def get_write_order(self, key: Key) -> Optional[List[Rifl]]:
        """Per-key order restricted to writes (reads commute; see add)."""
        order = self._order_per_key.get(key)
        if order is None:
            return None
        return [r for r in order if (key, r) not in self._reads]

    def keys(self) -> Iterator[Key]:
        return iter(self._order_per_key.keys())

    def __len__(self) -> int:
        return len(self._order_per_key)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, ExecutionOrderMonitor)
            and self._order_per_key == other._order_per_key
        )

    def __repr__(self) -> str:
        return f"ExecutionOrderMonitor({self._order_per_key})"
