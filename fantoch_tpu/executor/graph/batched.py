"""Batched device-resolved DependencyGraph — the north-star integration.

Replaces the per-add host Tarjan walk of
fantoch_ps/src/executor/graph/mod.rs:215-644 + tarjan.rs:99-319 with the
batched device resolver (fantoch_tpu/ops/graph_resolve.py) at the same
seam: ``BatchedDependencyGraph`` is a drop-in for ``DependencyGraph``
(select with ``Config.batched_graph_executor``), reusing its vertex /
pending indexes, cross-shard request plumbing and GC bookkeeping, and
overriding only the ordering core.

How one ``handle_add`` resolves:

  1. the whole committed-but-unexecuted backlog (arrival order from the
     insertion-ordered VertexIndex) becomes one batch; each vertex's deps
     are pruned against the executed clock (-> TERMINAL), mapped to batch
     indices, or marked MISSING when not committed here yet (missing deps
     are recorded in the PendingIndex, which also yields the cross-shard
     info requests of mod.rs:300-375);
  2. out-degree <= 1 batches take the exact O(log B) functional path
     (resolve_functional); wider batches take resolve_general;
  3. vertices the device resolved are executed in the returned
     (rank, SCC leader, dot) order — SCCs contiguous and dot-sorted,
     every SCC after all SCCs it depends on, matching the order contract
     of the host oracle (tarjan.rs:15, mod.rs:490-525);
  4. ``stuck`` residues (rare 3+-cycles with strictly one-directional
     conflict visibility that the device pass cannot collapse) are closed
     under dependencies, so they are handed to the host TarjanSCCFinder
     oracle, in arrival order, after all device-resolved vertices.

Per-key execution order is identical to the host oracle's: conflicting
commands are always dependency-linked, so their relative order is forced
by the condensation topology (or by dot order inside an SCC) — both of
which the device order preserves.  Whole-batch order may interleave
*independent* commands differently, which the correctness argument
explicitly permits (fantoch/src/executor/monitor.rs agreement is per key).

Batch shapes are padded to powers of two so XLA compiles O(log^2) distinct
programs, and device results are fetched with one host sync per resolve.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import ExecutorMetricsKind
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
from fantoch_tpu.executor.graph.tarjan import FinderResult, Vertex
from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_functional,
    resolve_general,
)


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class BatchedDependencyGraph(DependencyGraph):
    """DependencyGraph whose ordering core is the batched device resolver."""

    def handle_add(self, dot: Dot, cmd: Command, deps, time: SysTime) -> None:
        assert self.executor_index == 0
        vertex = Vertex(dot, cmd, list(deps), time)
        if self._vertex_index.index(vertex) is not None:
            raise AssertionError(
                f"p{self._process_id}: tried to index already indexed {dot}"
            )
        self._resolve_backlog(time)

    def handle_add_batch(self, adds, time: SysTime) -> None:
        """Bulk add: index the whole batch, then resolve once — one device
        round-trip for the entire queue drain instead of one per add."""
        assert self.executor_index == 0
        for dot, cmd, deps in adds:
            vertex = Vertex(dot, cmd, list(deps), time)
            if self._vertex_index.index(vertex) is not None:
                raise AssertionError(
                    f"p{self._process_id}: tried to index already indexed {dot}"
                )
        self._resolve_backlog(time)

    def _check_pending(self, dots, time: SysTime) -> None:
        """Executed-dot notifications (request replies) re-resolve the
        backlog as a whole; no per-dot cascade is needed.  The dots were
        executed (possibly remotely — RequestReplyExecuted), so their
        pending-index entries are dropped like the host cascade does
        (deps_graph.py _check_pending's remove)."""
        assert self.executor_index == 0
        for dot in dots:
            self._pending_index.remove(dot)
        self._resolve_backlog(time)

    # --- the batched ordering core ---

    def _resolve_backlog(self, time: SysTime) -> None:
        dots: List[Dot] = list(self._vertex_index.dots())  # arrival order
        if not dots:
            return
        batch = len(dots)
        index_of: Dict[Dot, int] = {d: i for i, d in enumerate(dots)}
        vertices: List[Vertex] = [self._vertex_index.find(d) for d in dots]

        rows: List[List[int]] = []
        width = 1
        for vertex in vertices:
            row: List[int] = []
            missing = set()
            for dep in vertex.deps:
                dep_dot = dep.dot
                if dep_dot == vertex.dot or self._executed_clock.contains(
                    dep_dot.source, dep_dot.sequence
                ):
                    continue
                j = index_of.get(dep_dot)
                if j is None:
                    row.append(MISSING)
                    missing.add(dep)
                else:
                    row.append(j)
            if missing:
                # PendingIndex dedupes re-sightings; first sighting of a
                # non-replicated dep yields a cross-shard request
                self._index_pending(vertex.dot, missing)
            rows.append(row)
            width = max(width, len(row))

        padded_b = _pad_pow2(batch)
        padded_w = _pad_pow2(width)
        dot_src = np.zeros(padded_b, dtype=np.int32)
        dot_seq = np.zeros(padded_b, dtype=np.int32)
        for i, d in enumerate(dots):
            dot_src[i] = d.source
            dot_seq[i] = d.sequence

        if width <= 1:
            dep_arr = np.full(padded_b, TERMINAL, dtype=np.int32)
            for i, row in enumerate(rows):
                if row:
                    dep_arr[i] = row[0]
            res = resolve_functional(dep_arr, dot_src, dot_seq)
            order = np.asarray(res.order)
            resolved = np.asarray(res.resolved)
            leader = np.asarray(res.leader)
            stuck = np.zeros(padded_b, dtype=bool)  # functional path is exact
        else:
            deps_arr = np.full((padded_b, padded_w), TERMINAL, dtype=np.int32)
            for i, row in enumerate(rows):
                deps_arr[i, : len(row)] = row
            res = resolve_general(deps_arr, dot_src, dot_seq)
            order = np.asarray(res.order)
            resolved = np.asarray(res.resolved)
            leader = np.asarray(res.leader)
            stuck = np.asarray(res.stuck)

        # emit device-resolved vertices in device order; SCC boundaries
        # (leader changes) drive the ChainSize metric like mod.rs:490-525
        scc_size = 0
        prev_leader = -1
        for i in order:
            if i >= batch or not resolved[i]:
                continue
            if leader[i] != prev_leader and scc_size:
                self._metrics.collect(ExecutorMetricsKind.CHAIN_SIZE, scc_size)
                scc_size = 0
            prev_leader = leader[i]
            scc_size += 1
            self._emit(dots[i], time)
        if scc_size:
            self._metrics.collect(ExecutorMetricsKind.CHAIN_SIZE, scc_size)

        # host-oracle fallback for stuck residues (closed under deps)
        if stuck[:batch].any():
            self._resolve_stuck([dots[i] for i in range(batch) if stuck[i]], time)

    def _emit(self, dot: Dot, time: SysTime) -> None:
        vertex = self._vertex_index.remove(dot)
        assert vertex is not None, "resolved dot must be indexed"
        self._executed_clock.add(dot.source, dot.sequence)
        if self._config.shard_count > 1:
            self._added_to_executed_clock.add(dot)
        self._pending_index.remove(dot)
        self._metrics.collect(
            ExecutorMetricsKind.EXECUTION_DELAY, vertex.duration_ms(time)
        )
        self._to_execute.append(vertex.cmd)

    def _resolve_stuck(self, stuck_dots: List[Dot], time: SysTime) -> None:
        """Host Tarjan oracle over the stuck residue, in arrival order
        (the ``stuck`` contract of ops/graph_resolve.resolve_general)."""
        for dot in stuck_dots:
            vertex = self._vertex_index.find(dot)
            if vertex is None:
                continue  # executed as part of an earlier stuck SCC
            result, _missing, _count = self._finder.strong_connect(
                True,
                dot,
                vertex,
                self._executed_clock,
                self._added_to_executed_clock,
                self._vertex_index,
            )
            for scc in self._finder.sccs():
                self._metrics.collect(ExecutorMetricsKind.CHAIN_SIZE, len(scc))
                for member in scc:
                    member_vertex = self._vertex_index.remove(member)
                    assert member_vertex is not None
                    self._pending_index.remove(member)
                    self._metrics.collect(
                        ExecutorMetricsKind.EXECUTION_DELAY,
                        member_vertex.duration_ms(time),
                    )
                    self._to_execute.append(member_vertex.cmd)
            self._finder.finalize(self._vertex_index)
            # stuck vertices are not missing-blocked (resolve_general
            # contract), so the oracle walk cannot hit a missing dep
            assert result is not FinderResult.MISSING_DEPENDENCIES, (
                f"stuck residue {dot} reached a missing dependency"
            )
