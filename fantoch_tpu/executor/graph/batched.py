"""Batched device-resolved DependencyGraph — the tensorized north-star seam.

Replaces the per-add host Tarjan walk of
fantoch_ps/src/executor/graph/mod.rs:215-644 + tarjan.rs:99-319 with the
batched device resolver (fantoch_tpu/ops/graph_resolve.py) at the same
seam: ``BatchedDependencyGraph`` is a drop-in for ``DependencyGraph``
(select with ``Config.batched_graph_executor``).

Round-3 redesign (VERDICT r2 item 2): commands cross the boundary **as
arrays**.  The backlog lives in append-only numpy columns — dot source /
sequence, conflict-key hash, commit time, packed dependency dots — grown
incrementally at add time (``handle_add_arrays`` appends whole array
chunks straight from the protocol's commit buffer; the (dot, cmd, deps)
tuple APIs remain as thin converters).  One resolve then:

  1. maps dependency dots to batch slots with a vectorized
     sort + searchsorted join (no per-dep dict lookups),
  2. prunes executed deps against a ``DeviceFrontier``
     (fantoch_tpu/ops/frontier.py — batch ``contains``, killing the
     per-dep Python ``executed_clock.contains`` of round 2),
  3. resolves on device: the keyed sort-based kernel for single-key
     functional batches (the hot path), ``resolve_general`` for wider
     ones; ``stuck`` residues (rare 3+-cycles) finish on the host Tarjan
     oracle over the stuck subgraph,
  4. emits in device order, advances the frontier in one batch add, and
     compacts the unresolved residue (missing-blocked rows simply wait for
     their dependency to arrive as a later add).

Resolution is **lazy**: adds mark the backlog dirty and the resolve runs
once per output drain (``commands_to_execute`` & friends), fixing the
round-2 O(B^2) behavior where every single ``handle_add`` re-resolved the
whole backlog.

Per-key execution order is identical to the host oracle's: conflicting
commands are always dependency-linked, so their relative order is forced
by the condensation topology (or by dot order inside an SCC) — both of
which the device order preserves.  Whole-batch order may interleave
*independent* commands differently, which the correctness argument
explicitly permits (fantoch/src/executor/monitor.rs agreement is per key).

Partial replication (round 4 — VERDICT r3 item 6): the array path now
covers ``shard_count > 1`` too.  The backlog keeps the original
``Dependency`` objects per row (shard sets must survive for cross-shard
requests); after a resolve, MISSING deps whose shard set excludes this
shard produce one info request each to the dep's target shard
(fantoch_ps/src/executor/graph/index.rs:171-205), and the secondary
(request-serving) executor answers peer shards straight from the
primary's array backlog — including *pending* rows, which is what breaks
cross-shard dependency cycles (mod.rs:300-375).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import ExecutorMetricsKind
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
from fantoch_tpu.ops.frontier import DeviceFrontier, pack_dots
from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_general,
    resolve_general_resident,
    resolve_general_staged,
    resolve_keyed_auto,
)
from fantoch_tpu.utils import key_hash as _framework_key_hash


def _use_resident_general() -> bool:
    """Route large multi-key batches through the device-resident
    peel-and-compact resolver (ONE dispatch + one fetch) instead of the
    host-orchestrated staged peeler (a state fetch + re-upload per
    stage, CPU-pinned to survive remote-dispatch rigs).  Default on —
    parity-tested bit-for-bit and faster on both rig shapes;
    ``FANTOCH_GENERAL_RESIDENT=0`` keeps the host-staged escape hatch."""
    import os

    return os.environ.get("FANTOCH_GENERAL_RESIDENT", "1") != "0"


# lazy module-level jax singleton: the resolve hot path used to re-run
# the import machinery (sys.modules probe + attribute walks) on every
# backlog flush; one cached (jax, jnp) pair serves every resolve
_JAX_MODS = None


def _jax_mods():
    global _JAX_MODS
    if _JAX_MODS is None:
        import jax
        import jax.numpy as jnp
        import jax.profiler  # noqa: F401 — TraceAnnotation in _resolve_backlog

        _JAX_MODS = (jax, jnp)
    return _JAX_MODS


_NO_DEP = np.int64(-1)  # packed-dep sentinel: no dependency in this slot
# below this backlog size, ask the keyed kernel for full structure so
# CHAIN_SIZE metrics stay exact (tests/sims); above it, skip the extra
# device sort and only collect aggregate metrics.  This is the built-in
# DEFAULT of the unified kernel-size gate: Config.graph_kernel_threshold
# beats the FANTOCH_GRAPH_KERNEL_THRESHOLD env var beats this value
# (executor/device_plane.resolve_threshold, the table-plane precedence)
_STRUCTURE_THRESHOLD = 4096


def key_hash(key: str) -> int:
    """Stable 31-bit conflict-key hash: the framework-wide key hash
    (fantoch_tpu/utils key_hash, the executor-routing hash of
    fantoch/src/util.rs:107) folded to int32 range for the device kernel.
    Collisions only cost resolver performance, not correctness."""
    return _framework_key_hash(key) & 0x7FFFFFFF


class _Backlog:
    """Append-only column store for committed-but-unexecuted commands."""

    __slots__ = ("cmds", "chunks", "scalars", "count")

    def __init__(self) -> None:
        self.cmds: List[Command] = []
        # each chunk: (src i64[b], seq i64[b], key i32[b], tms f64[b],
        #             deps i64[b, w] packed dots, _NO_DEP padded)
        self.chunks: List[Tuple[np.ndarray, ...]] = []
        self.scalars: List[Tuple[int, int, int, float, Tuple[int, ...]]] = []
        self.count = 0

    def append_arrays(self, src, seq, key, tms, deps, cmds) -> None:
        assert len(src) == len(cmds)
        self.chunks.append((src, seq, key, tms, deps))
        self.cmds.extend(cmds)
        self.count += len(src)

    def append_one(self, src, seq, key, tms, dep_packed, cmd) -> None:
        self.scalars.append((src, seq, key, tms, dep_packed))
        self.cmds.append(cmd)
        self.count += 1

    def columns(self):
        """Materialize (src, seq, key, tms, deps[B, W]) over everything."""
        chunks = list(self.chunks)
        if self.scalars:
            width = max(len(d) for *_x, d in self.scalars)
            width = max(width, 1)
            src = np.fromiter((s for s, *_ in self.scalars), np.int64)
            seq = np.fromiter((q for _, q, *_ in self.scalars), np.int64)
            key = np.fromiter((k for _, _, k, *_ in self.scalars), np.int32)
            tms = np.fromiter((t for _, _, _, t, _ in self.scalars), np.float64)
            deps = np.full((len(self.scalars), width), _NO_DEP)
            for i, (*_x, d) in enumerate(self.scalars):
                deps[i, : len(d)] = d
            chunks.append((src, seq, key, tms, deps))
        if not chunks:
            empty = np.empty(0, np.int64)
            return empty, empty, empty.astype(np.int32), empty.astype(np.float64), np.empty((0, 1), np.int64)
        width = max(c[4].shape[1] for c in chunks)
        dep_mats = []
        for c in chunks:
            mat = c[4]
            if mat.shape[1] < width:
                pad = np.full((mat.shape[0], width - mat.shape[1]), _NO_DEP)
                mat = np.concatenate([mat, pad], axis=1)
            dep_mats.append(mat)
        return (
            np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]),
            np.concatenate([c[2] for c in chunks]),
            np.concatenate([c[3] for c in chunks]),
            np.concatenate(dep_mats, axis=0),
        )

    def replace(self, src, seq, key, tms, deps, cmds) -> None:
        self.chunks = [(src, seq, key, tms, deps)] if len(src) else []
        self.scalars = []
        self.cmds = cmds
        self.count = len(cmds)


class BatchedDependencyGraph(DependencyGraph):
    """DependencyGraph whose ordering core is the batched device resolver."""

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        super().__init__(process_id, shard_id, config)
        self._array_mode = True
        self._multi_shard = config.shard_count > 1
        if self._multi_shard:
            # multi-shard bookkeeping (single-shard pays none of this):
            # packed dot -> (cmd, deps) for request serving from the
            # backlog; packed dep dot -> shard set; the set of remote deps
            # already requested; the primary graph a secondary serves from
            self._by_dot: dict = {}
            self._dep_shards: dict = {}
            self._requested: set = set()
            self._primary: Optional["BatchedDependencyGraph"] = None
        if self._array_mode:
            from fantoch_tpu.core.ids import all_process_ids

            ids = [pid for pid, _ in all_process_ids(config.shard_count, config.n)]
            self._frontier = DeviceFrontier(ids)
            # keep the inherited name pointing at the frontier so the host
            # Tarjan oracle (stuck residues) sees the same executed set
            self._executed_clock = self._frontier  # type: ignore[assignment]
            self._backlog = _Backlog()
            self._dirty = False
            self._last_time: Optional[SysTime] = None
            self._native_auto: Optional[bool] = None
            # the unified kernel-size gate (config > env > default)
            from fantoch_tpu.executor.device_plane import resolve_threshold

            self._structure_threshold = resolve_threshold(
                config.graph_kernel_threshold,
                "FANTOCH_GRAPH_KERNEL_THRESHOLD",
                _STRUCTURE_THRESHOLD,
            )
            # device-resident backlog plane (executor/graph/graph_plane.py):
            # the host-column machinery below stays the oracle twin.
            # Single-shard only — Dependency shard sets must survive on
            # host for cross-shard requests (ROADMAP item 2's sharded
            # planes are the multi-shard story)
            from fantoch_tpu.executor.graph.graph_plane import (
                graph_plane_enabled,
            )

            if config.device_graph_plane and self._multi_shard:
                raise ValueError(
                    "device_graph_plane requires shard_count == 1 (the "
                    "backlog plane keeps no per-dep shard sets)"
                )
            self._plane = None
            if graph_plane_enabled(config) and not self._multi_shard:
                from fantoch_tpu.executor.graph.graph_plane import (
                    DeviceGraphPlane,
                )
                from fantoch_tpu.ops.pallas_resolve import (
                    apply_pallas_config,
                )

                # fold Config.pallas_kernels into the kernel route before
                # the plane's first dispatch (config > env > backend
                # default)
                apply_pallas_config(config)
                self._plane = DeviceGraphPlane(
                    process_id, shard_id, config, self._frontier,
                    self._metrics,
                    structure_threshold=self._structure_threshold,
                )
                # arm the fault plane (deadline + shadow-check) from the
                # config; runners re-seed and attach injectors on top
                self._plane.configure_faults(config, process_id=process_id)
            # opt-in array drain (VERDICT r3 item 3): consumers that don't
            # need Command objects (array-native planes, benches) read the
            # execution order as (src, seq) columns and skip the 250k-object
            # materialization entirely.  Off by default so object-drain
            # consumers don't accumulate an undrained mirror.
            self.record_order_arrays = False
            self._order_arrays: List[Tuple[np.ndarray, np.ndarray]] = []

    # --- add paths ---

    def handle_add(self, dot: Dot, cmd: Command, deps, time: SysTime) -> None:
        assert self.executor_index == 0
        if not self._array_mode:
            return super().handle_add(dot, cmd, list(deps), time)
        self._append_tuple(dot, cmd, deps, time)
        self._dirty = True
        self._last_time = time

    def handle_add_batch(self, adds, time: SysTime) -> None:
        """Bulk tuple add: one resolve for the batch on the next drain."""
        assert self.executor_index == 0
        if not self._array_mode:
            return super().handle_add_batch(adds, time)
        for dot, cmd, deps in adds:
            self._append_tuple(dot, cmd, deps, time)
        self._dirty = True
        self._last_time = time

    def handle_add_arrays(
        self,
        dot_src: np.ndarray,  # int64[b]
        dot_seq: np.ndarray,  # int64[b]
        key: np.ndarray,  # int32[b] conflict-key hash (-1 = multi-key)
        dep_dots: np.ndarray,  # int64[b, w] packed dep dots (pack_dots), -1 pad
        cmds: List[Command],
        time: SysTime,
    ) -> None:
        """The tensorized seam: the protocol's commit buffer lands here as
        whole arrays — no per-command Python in the executor."""
        assert self.executor_index == 0 and self._array_mode
        assert not self._multi_shard, (
            "array adds carry no shard sets; multi-shard commits arrive as "
            "per-command GraphAdd (graph_protocol.py commit buffer gating)"
        )
        tms = np.full(len(cmds), float(time.millis()), np.float64)
        self._backlog.append_arrays(
            dot_src.astype(np.int64, copy=False),
            dot_seq.astype(np.int64, copy=False),
            key.astype(np.int32, copy=False),
            tms,
            dep_dots.astype(np.int64, copy=False),
            cmds,
        )
        self._dirty = True
        self._last_time = time

    def _append_tuple(self, dot: Dot, cmd: Command, deps, time: SysTime) -> None:
        if cmd.key_count(self._shard_id) == 1:
            khash = key_hash(next(iter(cmd.keys(self._shard_id))))
        else:
            khash = -1
        packed = tuple(
            (int(d.dot.source) << 32) | int(d.dot.sequence)
            for d in deps
            if d.dot != dot  # self-dependency pruned (tarjan.py:129)
        )
        if self._multi_shard:
            # shard sets must survive: cross-shard requests need them, and
            # request replies forward the full Dependency list
            self._by_dot[(int(dot.source) << 32) | int(dot.sequence)] = (
                cmd, list(deps)
            )
            for d in deps:
                if d.shards is not None:
                    self._dep_shards.setdefault(
                        (int(d.dot.source) << 32) | int(d.dot.sequence),
                        d.shards,
                    )
        self._backlog.append_one(
            int(dot.source), int(dot.sequence), khash, float(time.millis()), packed, cmd
        )

    # --- executed notifications / request replies ---

    def handle_executed(self, dots, _time: SysTime) -> None:
        if not self._array_mode:
            return super().handle_executed(dots, _time)
        if self.executor_index > 0 and dots:
            src = np.fromiter((d.source for d in dots), np.int64, len(dots))
            seq = np.fromiter((d.sequence for d in dots), np.int64, len(dots))
            self._frontier.add_batch(src, seq)

    def _check_pending(self, dots, time: SysTime) -> None:
        """Executed-dot notifications just mark the backlog dirty: the next
        drain re-resolves with the updated frontier."""
        assert self.executor_index == 0
        if not self._array_mode:
            return super()._check_pending(dots, time)
        self._dirty = True

    def handle_noop(self, dot: Dot, time: SysTime) -> None:
        if self._array_mode and self._plane is not None:
            # the plane's waiter index patches every MISSING cell waiting
            # on the noop dot to TERMINAL on the next dispatch
            self._plane.note_noop(int(dot.source), int(dot.sequence))
        super().handle_noop(dot, time)

    def handle_request_reply(self, infos, time: SysTime) -> None:
        if not self._array_mode:
            return super().handle_request_reply(infos, time)
        from fantoch_tpu.executor.graph.deps_graph import RequestReplyInfo

        for info in infos:
            if isinstance(info, RequestReplyInfo):
                self.handle_add(info.dot, info.cmd, info.deps, time)
            else:
                self._frontier.add(info.dot.source, info.dot.sequence)
                self._added_to_executed_clock.add(info.dot)
                packed = (int(info.dot.source) << 32) | int(info.dot.sequence)
                self._dep_shards.pop(packed, None)
                self._requested.discard(packed)
                self._dirty = True

    # --- cross-shard request serving (secondary executor; mod.rs:300-375) ---

    def share_vertex_index(self, primary: "DependencyGraph") -> None:
        super().share_vertex_index(primary)
        if self._multi_shard:
            self._primary = primary  # serve requests from the array backlog

    def process_requests(self, from_shard: ShardId, dots, time: SysTime) -> None:
        """Answer a peer shard's dependency-info request from the primary's
        array backlog — including rows still *pending* there (answering
        only executed dots deadlocks cross-shard dependency cycles)."""
        if not self._array_mode:
            return super().process_requests(from_shard, dots, time)
        assert self.executor_index > 0
        from fantoch_tpu.executor.graph.deps_graph import (
            RequestReplyExecuted,
            RequestReplyInfo,
        )

        source = self._primary if self._primary is not None else self
        for dot in dots:
            packed = (int(dot.source) << 32) | int(dot.sequence)
            entry = source._by_dot.get(packed)
            if entry is not None:
                cmd, deps = entry
                assert not cmd.replicated_by(from_shard), (
                    f"{dot} is replicated by requesting shard {from_shard}"
                )
                self._out_request_replies.setdefault(from_shard, []).append(
                    RequestReplyInfo(dot, cmd, deps)
                )
            elif self._frontier.contains(dot.source, dot.sequence) or (
                source is not self
                and source._frontier.contains(dot.source, dot.sequence)
            ):
                self._out_request_replies.setdefault(from_shard, []).append(
                    RequestReplyExecuted(dot)
                )
            else:
                # not known yet: buffer and retry on cleanup
                self._buffered_in_requests.setdefault(from_shard, set()).add(dot)

    def _note_emitted(self, src_rows, seq_rows) -> None:
        """Multi-shard emit bookkeeping: drop served entries (and the
        request/shard-set records for executed deps — the PendingIndex
        removes on execution too, index.rs remove) and record the executed
        dots for the GraphExecuted broadcast (to_executors)."""
        if not self._multi_shard:
            return
        for p in pack_dots(src_rows, seq_rows).tolist():
            self._by_dot.pop(p, None)
            self._dep_shards.pop(p, None)
            self._requested.discard(p)
            self._added_to_executed_clock.add(Dot(p >> 32, p & 0xFFFFFFFF))

    def _request_missing(self, dep_rows, deps, remaining_mask) -> None:
        """One info request per first-sighted missing dep whose shard set
        excludes this shard (PendingIndex.index semantics,
        index.rs:171-205); local missing deps arrive via local commits."""
        miss_slots = (dep_rows == MISSING) & remaining_mask[:, None]
        if not miss_slots.any():
            return
        requests = 0
        for packed in np.unique(deps[miss_slots]).tolist():
            if packed in self._requested:
                continue
            self._requested.add(packed)
            shards = self._dep_shards.get(packed)
            if shards is None or self._shard_id in shards:
                continue
            dot = Dot(packed >> 32, packed & 0xFFFFFFFF)
            self._out_requests.setdefault(
                dot.target_shard(self._config.n), set()
            ).add(dot)
            requests += 1
        if requests:
            self._metrics.aggregate(ExecutorMetricsKind.OUT_REQUESTS, requests)

    # --- lazy resolution at the output drains ---

    def command_to_execute(self) -> Optional[Command]:
        self._flush()
        return super().command_to_execute()

    def commands_to_execute(self) -> List[Command]:
        self._flush()
        return super().commands_to_execute()

    def monitor_pending(self, time: SysTime):
        if not self._array_mode:
            return super().monitor_pending(time)
        if self._plane is not None:
            self._flush(time)
            self._plane.drain_all()
            self._drain_plane_emissions()
            return self._plane.monitor_pending(time)
        self._flush(time)
        # liveness watchdog (index.rs:53-103): after a resolve, every
        # still-pending row must be *transitively* missing-blocked — the
        # resolvers emit everything else.  A per-row check (not the r3
        # whole-backlog aggregate): an old row whose dependency closure
        # contains no missing dep means an execution was lost (e.g. a
        # dropped executed-notification) — panic naming the dots, exactly
        # like the reference's per-command pending monitor.
        if not self._backlog.count:
            return None
        src, seq, _key, tms, deps = self._backlog.columns()
        from fantoch_tpu.executor.graph.indexes import MONITOR_PENDING_THRESHOLD_MS

        pending_for = float(time.millis()) - tms
        old = pending_for >= MONITOR_PENDING_THRESHOLD_MS
        # the bounded-wait mask has its own (possibly lower) threshold —
        # it must not loosen the lost-execution check, which stays on
        # `old`, nor be floored by it (see deps_graph.monitor_pending)
        fail_ms = self._config.executor_pending_fail_ms
        ripe = pending_for >= fail_ms if fail_ms is not None else None
        if not old.any() and (ripe is None or not ripe.any()):
            return None
        dep_rows = self._map_deps(src, seq, deps)
        batch = len(src)
        blocked = (dep_rows == MISSING).any(axis=1)
        # missing dependency dots of old blocked rows: returned so the
        # runner can nudge the protocol's recovery plane (deps_graph
        # monitor_pending contract)
        nudge = {
            Dot(int(d) >> 32, int(d) & 0xFFFFFFFF)
            for i in np.nonzero(blocked & old)[0]
            for d, r in zip(deps[i], dep_rows[i])
            if r == MISSING and d >= 0
        }
        # bounded wait (Config.executor_pending_fail_ms): a row blocked on
        # a missing dependency past the fail bound raises a typed error —
        # a dot whose coordinator crashed before broadcasting commit never
        # commits, and silently waiting on it is a deadlock
        if ripe is not None:
            stalled = blocked & ripe
            if stalled.any():
                missing_map = {}
                for i in np.nonzero(stalled)[0][:8]:
                    missing_map[Dot(int(src[i]), int(seq[i]))] = {
                        Dot(int(d) >> 32, int(d) & 0xFFFFFFFF)
                        for d, r in zip(deps[i], dep_rows[i])
                        if r == MISSING and d >= 0
                    }
                from fantoch_tpu.errors import StalledExecutionError

                raise StalledExecutionError(
                    self._process_id,
                    missing_map,
                    int(pending_for[stalled].max()),
                    self._config.recovery_delay_ms,
                )
        # forward-propagate blockedness to dependents, vectorized with an
        # early exit the moment every old row is covered (the common case:
        # one or two passes; the full fixpoint only runs on the panic path)
        valid = dep_rows >= 0
        safe = np.clip(dep_rows, 0, batch - 1)
        while True:
            lost = old & ~blocked
            if not lost.any():
                return nudge
            grown = blocked | np.where(valid, blocked[safe], False).any(axis=1)
            if (grown == blocked).all():
                break
            blocked = grown
        if lost.any():
            dots = [
                Dot(int(src[i]), int(seq[i]))
                for i in np.nonzero(lost)[0][:8]
            ]
            raise AssertionError(
                f"p{self._process_id}: {int(lost.sum())} commands pending "
                f"without missing dependencies: {dots}"
            )
        return nudge

    def _flush(self, time: Optional[SysTime] = None) -> None:
        if not self._array_mode or not self._dirty:
            if (
                self._array_mode
                and self._plane is not None
                and self._plane._emitted
            ):
                # depth-K pipelined serving: results of earlier rounds
                # may have drained during a later feed — deliver them
                # even when nothing new is dirty
                self._drain_plane_emissions()
            return
        self._dirty = False
        if time is None:
            time = self._last_time
        if time is None:
            from fantoch_tpu.core.timing import RunTime

            time = RunTime()
        self._resolve_backlog(time)

    # --- the batched ordering core ---

    def _map_deps(self, src, seq, deps) -> np.ndarray:
        """Vectorized dep-dot -> batch-slot join.  Returns int32[B, W] with
        TERMINAL (executed / none / self) and MISSING sentinels.

        Join strategy: dot sequences are near-dense per source (they come
        from per-process DotGens), so a direct-addressed (source, seq)
        table is one scatter + one gather — ~10x cheaper than the
        sort+searchsorted join at 250k rows.  Falls back to the sort join
        when the address space would be sparse (pathological seq gaps)."""
        batch, width = deps.shape
        flat = deps.reshape(-1)
        valid = flat >= 0
        out = np.full(batch * width, TERMINAL, dtype=np.int32)
        if not valid.any():
            # still run the join machinery's duplicate-dot check: a dot
            # delivered twice must raise even in a no-conflict batch
            self._join_rows(src, seq, flat[:0])
        else:
            v = flat[valid]
            slot = self._join_rows(src, seq, v)
            in_batch = slot >= 0
            # not in batch: executed -> TERMINAL, else MISSING
            dep_src = v >> 32
            dep_seq = v & 0xFFFFFFFF
            executed = self._frontier.contains_batch(dep_src, dep_seq)
            res = np.where(
                in_batch, slot, np.where(executed, TERMINAL, MISSING)
            ).astype(np.int32)
            # self-dependency guard (array chunks may carry them)
            rows = np.nonzero(valid)[0] // width
            res = np.where(res == rows, TERMINAL, res)
            out[valid] = res
        return out.reshape(batch, width)

    def _join_rows(self, src, seq, v) -> np.ndarray:
        """Row index per packed dep dot in ``v`` (-1 = not in batch)."""
        batch = len(src)
        if batch == 0:
            return np.full(len(v), -1, dtype=np.int64)
        src_lo, src_hi = int(src.min()), int(src.max())
        seq_lo, seq_hi = int(seq.min()), int(seq.max())
        span = (src_hi - src_lo + 1) * (seq_hi - seq_lo + 1)
        # n sources x a dense seq range is ~n*batch: allow up to 16x
        # (int32 table, 16 MB at 250k rows) before falling back to sorting
        if span <= 16 * batch + (1 << 16):
            table = np.full(span, -1, dtype=np.int32)
            width_seq = seq_hi - seq_lo + 1
            addr = (src - src_lo) * width_seq + (seq - seq_lo)
            rng = np.arange(batch, dtype=np.int32)
            table[addr] = rng
            # duplicate-dot detection: a duplicate overwrites its earlier
            # row, so the gather-back no longer matches arange
            assert (table[addr] == rng).all(), "duplicate dot added"
            dep_src = v >> 32
            dep_seq = v & 0xFFFFFFFF
            in_range = (
                (dep_src >= src_lo) & (dep_src <= src_hi)
                & (dep_seq >= seq_lo) & (dep_seq <= seq_hi)
            )
            dep_addr = np.where(
                in_range, (dep_src - src_lo) * width_seq + (dep_seq - seq_lo), 0
            )
            return np.where(in_range, table[dep_addr], -1)
        packed = pack_dots(src, seq)
        sort_idx = np.argsort(packed, kind="stable").astype(np.int64)
        sorted_packed = packed[sort_idx]
        assert (np.diff(sorted_packed) > 0).all(), "duplicate dot added"
        j = np.searchsorted(sorted_packed, v)
        j = np.minimum(j, batch - 1)
        return np.where(sorted_packed[j] == v, sort_idx[j], -1)

    def _resolve_backlog(self, time: SysTime) -> None:
        if not self._backlog.count:
            if self._plane is not None and self._plane.has_patches:
                # patches with no new feed (noop resolutions): the plane
                # still needs one dispatch to wake waiting residents
                self._plane.flush(time)
                self._drain_plane_emissions()
            return
        # host-side latency histogram + device-side xprof annotation
        # (SURVEY §5: jax.profiler is the TPU-native tracer; the host span
        # lands in fantoch_tpu.utils.prof's registry).  jax is a lazy
        # module-level singleton — the per-resolve import machinery used
        # to re-run on every backlog flush
        jax, _jnp = _jax_mods()

        from fantoch_tpu.utils.prof import elapsed

        with elapsed("BatchedDependencyGraph._resolve_backlog"), (
            jax.profiler.TraceAnnotation("graph_resolve")
        ):
            self._resolve_backlog_inner(time)

    def _use_native_resolver(self) -> bool:
        """The native C++ resolver replaces the XLA kernels on CPU backends
        (Config.host_native_resolver; auto = native when built and the
        default backend is CPU — CPU XLA sorts lose to a single host
        Tarjan pass, while accelerators keep the device kernels)."""
        forced = self._config.host_native_resolver
        from fantoch_tpu import native

        if forced is not None:
            if forced and not native.available():
                raise RuntimeError(
                    "host_native_resolver=True but the native library is "
                    "unavailable (toolchain missing?); use None for "
                    "auto-fallback"
                )
            return bool(forced)
        if self._native_auto is None:
            jax, _jnp = _jax_mods()

            self._native_auto = (
                jax.default_backend() == "cpu" and native.available()
            )
        return self._native_auto

    def _resolve_native(self, dep_rows, src, seq, batch):
        """Whole-backlog resolve on the native host Tarjan (CSR over the
        already-joined dep slots; TERMINAL pruned, MISSING kept as -2 —
        the same contract as the stuck-residue call).  Returns emitted
        rows; never leaves stuck residues (a full Tarjan resolves every
        non-missing-blocked SCC)."""
        from fantoch_tpu import native

        mask = dep_rows != TERMINAL
        counts = mask.sum(axis=1, dtype=np.int64)
        offsets = np.zeros(batch + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        targets = dep_rows[mask].astype(np.int32)  # row-major slot order
        packed = pack_dots(src, seq)
        out = native.resolve_sccs(offsets.astype(np.int32), targets, packed)
        if out is None:
            return None
        order, sizes = out
        if batch <= self._structure_threshold and len(order):
            # exact CHAIN_SIZE only at small sizes (the walk is O(#SCCs)
            # Python — same gating as the keyed path's want_structure)
            pos, scc_sizes = 0, []
            while pos < len(order):
                scc_sizes.append(int(sizes[pos]))
                pos += int(sizes[pos])
            self._metrics.collect_many(ExecutorMetricsKind.CHAIN_SIZE, scc_sizes)
        return order.astype(np.int64)

    def _resolve_backlog_inner(self, time: SysTime) -> None:
        if self._plane is not None:
            return self._resolve_backlog_plane(time)
        src, seq, key, tms, deps = self._backlog.columns()
        batch = len(src)
        dep_rows = self._map_deps(src, seq, deps)

        # host arrival-order fast path (the host twin of the device
        # kernel's verify-don't-compute shortcut, graph_resolve.py): when
        # every in-batch dependency points at an *earlier* row and nothing
        # is missing, the graph is a DAG whose arrival order is already a
        # valid execution order — emit everything with zero resolver work.
        # Gated to large batches so small (sim/test) batches keep exact
        # CHAIN_SIZE structure from the full resolvers.
        if (
            batch > self._structure_threshold
            and bool((dep_rows < np.arange(batch, dtype=np.int32)[:, None]).all())
            and not bool((dep_rows == MISSING).any())
        ):
            if self.record_order_arrays:
                self._order_arrays.append((src, seq))
            else:
                self._to_execute.extend(self._backlog.cmds)
            self._frontier.add_batch(src, seq)
            self._note_emitted(src, seq)
            now = float(time.millis())
            self._metrics.collect_many(
                ExecutorMetricsKind.EXECUTION_DELAY, np.maximum(now - tms, 0.0)
            )
            self._backlog.replace(
                src[:0], seq[:0], key[:0], tms[:0], deps[:0], []
            )
            return

        if self._use_native_resolver():
            emitted = self._resolve_native(dep_rows, src, seq, batch)
            if emitted is not None:
                remaining_mask = np.ones(batch, dtype=bool)
                if len(emitted):
                    self._emit_rows(emitted, src, seq, tms, time)
                    remaining_mask[emitted] = False
                self._shrink_backlog(
                    remaining_mask, src, seq, key, tms, deps, dep_rows
                )
                return

        # compress to functional form when every row has <= 1 live dep
        live = dep_rows != TERMINAL
        live_counts = live.sum(axis=1)
        functional = bool((live_counts <= 1).all())
        src32 = src.astype(np.int32)
        seq32 = (seq - seq.min()).astype(np.int32) if batch else src32

        jax, jnp = _jax_mods()

        if functional and bool((key >= 0).all()):
            col = np.where(
                live_counts > 0,
                dep_rows[np.arange(batch), np.argmax(live, axis=1)],
                TERMINAL,
            ).astype(np.int32)
            # pad to pow2 so XLA compiles O(log) distinct programs, not one
            # per backlog size (the lazy flush sees arbitrary sizes).  Pad
            # rows carry a private key so they form their own run, resolve
            # as singletons, and are filtered out of the emitted prefix.
            padded_b = _pad_pow2(batch)
            # distinct pad keys: each pad row is its own single-row run
            # (one shared key would make every non-head pad row fail the
            # in-run link check and flood the residual)
            pk = np.iinfo(np.int32).max - np.arange(padded_b, dtype=np.int32)
            pc = np.full(padded_b, TERMINAL, dtype=np.int32)
            ps = np.zeros(padded_b, np.int32)
            pq = np.zeros(padded_b, np.int32)
            pk[:batch] = key
            pc[:batch] = col
            ps[:batch] = src32
            pq[:batch] = seq32
            want_structure = batch <= self._structure_threshold
            res = resolve_keyed_auto(
                jnp.asarray(pk),
                jnp.asarray(pc),
                jnp.asarray(ps),
                jnp.asarray(pq),
                return_structure=want_structure,
            )
            # one blocking transfer for all result fields (async copies
            # issued per leaf, then one wait) — per-field np.asarray would
            # pay a device round trip each on a remote-dispatch rig
            res = jax.device_get(res)
            order = res.order
            n_res = int(res.n_resolved)
            emitted = order[:n_res]
            emitted = emitted[emitted < batch]  # drop resolved pad rows
            n_res = len(emitted)
            stuck_rows = None
            if want_structure and n_res:
                leaders = res.leader[emitted]
                sizes = np.diff(
                    np.concatenate(
                        [[0], np.nonzero(np.diff(leaders))[0] + 1, [n_res]]
                    )
                )
                self._metrics.collect_many(ExecutorMetricsKind.CHAIN_SIZE, sizes)
        elif batch > self._structure_threshold:
            # large multi-key batch: the peel-and-compact peeler's cost
            # tracks the per-level live set instead of B x depth, so deep
            # alternating chains don't fall off the fixed-budget cliff
            # (VERDICT r3 weak #3); structure metrics are skipped at this
            # size, matching the keyed path's gating.  The resident
            # variant runs the whole stage schedule as ONE dispatch with
            # the state device-resident between stages (no per-stage
            # host round-trips — the r13 fallback-cliff fix)
            if _use_resident_general():
                # pad to pow2 so XLA compiles O(log) distinct programs as
                # backlog sizes vary; pad rows resolve as rank-0
                # singletons and are dropped from the emitted prefix
                padded_b = _pad_pow2(batch)
                padded_w = _pad_pow2(max(dep_rows.shape[1], 1))
                mat = np.full((padded_b, padded_w), TERMINAL, dtype=np.int32)
                mat[:batch, : dep_rows.shape[1]] = dep_rows
                ps = np.zeros(padded_b, np.int32)
                pq = np.zeros(padded_b, np.int32)
                ps[:batch] = src32
                pq[:batch] = seq32
                res = resolve_general_resident(
                    jnp.asarray(mat), jnp.asarray(ps), jnp.asarray(pq)
                )
                # one blocking transfer for all result fields
                res = jax.device_get(res)
                order = res.order
                order = order[order < batch]
                emitted = order[res.resolved[order]]
                n_res = len(emitted)
                stuck = res.stuck[:batch]
                stuck_rows = np.nonzero(stuck)[0] if stuck.any() else None
            else:
                # host-orchestrated escape hatch (results host-side)
                res = resolve_general_staged(dep_rows, src32, seq32)
                order = res.order
                emitted = order[res.resolved[order]]
                n_res = len(emitted)
                stuck_rows = (
                    np.nonzero(res.stuck)[0] if res.stuck.any() else None
                )
        else:
            padded_b = _pad_pow2(batch)
            padded_w = _pad_pow2(max(dep_rows.shape[1], 1))
            mat = np.full((padded_b, padded_w), TERMINAL, dtype=np.int32)
            mat[:batch, : dep_rows.shape[1]] = dep_rows
            ps = np.zeros(padded_b, np.int32)
            pq = np.zeros(padded_b, np.int32)
            ps[:batch] = src32
            pq[:batch] = seq32
            res = resolve_general(jnp.asarray(mat), jnp.asarray(ps), jnp.asarray(pq))
            res = jax.device_get(res)  # all fields in one blocking transfer
            order = res.order
            order = order[order < batch]
            emitted = order[res.resolved[order]]
            n_res = len(emitted)
            stuck = res.stuck[:batch]
            stuck_rows = np.nonzero(stuck)[0] if stuck.any() else None
            if n_res:
                leaders = res.leader[emitted]
                sizes = np.diff(
                    np.concatenate(
                        [[0], np.nonzero(np.diff(leaders))[0] + 1, [n_res]]
                    )
                )
                self._metrics.collect_many(ExecutorMetricsKind.CHAIN_SIZE, sizes)

        remaining_mask = np.ones(batch, dtype=bool)
        if n_res:
            self._emit_rows(emitted, src, seq, tms, time)
            remaining_mask[emitted] = False

        if stuck_rows is not None and len(stuck_rows):
            stuck_rows = _close_stuck_set(stuck_rows, dep_rows, remaining_mask)
        if stuck_rows is not None and len(stuck_rows):
            oracle_emitted = self._resolve_stuck_rows(
                stuck_rows, src, seq, deps, tms, time
            )
            remaining_mask[oracle_emitted] = False

        self._shrink_backlog(remaining_mask, src, seq, key, tms, deps, dep_rows)

    def _shrink_backlog(
        self, remaining_mask, src, seq, key, tms, deps, dep_rows=None
    ) -> None:
        if self._multi_shard and dep_rows is not None:
            self._request_missing(dep_rows, deps, remaining_mask)
        if self._multi_shard and len(self._dep_shards) > 4 * max(
            int(remaining_mask.sum()), 64
        ):
            # amortized GC of the dep-shard / requested records: only deps
            # still referenced by surviving rows matter (a dep that
            # executed before its dependent arrived would otherwise leak
            # forever — _note_emitted only covers locally emitted dots).
            # Dropping an in-flight request record at worst re-requests.
            live = set(deps[remaining_mask][deps[remaining_mask] >= 0].tolist())
            self._dep_shards = {
                p: s for p, s in self._dep_shards.items() if p in live
            }
            self._requested &= live
        keep = np.nonzero(remaining_mask)[0]
        cmds = self._backlog.cmds
        self._backlog.replace(
            src[keep],
            seq[keep],
            key[keep],
            tms[keep],
            deps[keep],
            [cmds[i] for i in keep],
        )

    # --- the device-resident backlog plane (Config.device_graph_plane) ---

    def _resolve_backlog_plane(self, time: SysTime) -> None:
        """One resident dispatch per flush: the feed columns transfer
        into the plane (new-row deltas are the only host->device traffic)
        and the whole pending window re-resolves in place.  The
        arrival-order fast path is preserved: with nothing resident, a
        backward-only no-missing feed emits host-side with zero
        dispatches, exactly like the host-column twin."""
        plane = self._plane
        src, seq, key, tms, deps = self._backlog.columns()
        batch = len(src)  # > 0: _resolve_backlog early-returns on empty
        if (
            batch > self._structure_threshold
            and plane.pending_count == 0
            and not plane.in_flight
            and not plane.has_patches
        ):
            dep_rows = self._map_deps(src, seq, deps)
            if (
                bool((dep_rows < np.arange(batch, dtype=np.int32)[:, None]).all())
                and not bool((dep_rows == MISSING).any())
            ):
                if self.record_order_arrays:
                    self._order_arrays.append((src, seq))
                else:
                    self._to_execute.extend(self._backlog.cmds)
                self._frontier.add_batch(src, seq)
                now = float(time.millis())
                self._metrics.collect_many(
                    ExecutorMetricsKind.EXECUTION_DELAY,
                    np.maximum(now - tms, 0.0),
                )
                self._backlog.replace(
                    src[:0], seq[:0], key[:0], tms[:0], deps[:0], []
                )
                return
        cmds = self._backlog.cmds
        self._backlog.replace(src[:0], seq[:0], key[:0], tms[:0], deps[:0], [])
        plane.feed(src, seq, key, tms, deps, cmds, time)
        self._drain_plane_emissions()

    def _drain_plane_emissions(self) -> None:
        for cmds, src, seq in self._plane.take_emitted():
            if self.record_order_arrays:
                self._order_arrays.append((src, seq))
            else:
                self._to_execute.extend(cmds)

    def flush_plane_pipeline(self, time: SysTime) -> None:
        """Retire every in-flight plane round and deliver its results —
        the end-of-stream flush of a depth-K pipelined serving loop
        (depth 1, the executor-pool default, never has delivery lag)."""
        self._last_time = time
        self._flush(time)
        if self._plane is not None:
            self._plane.drain_all()
            self._drain_plane_emissions()

    def resolve_now(self, time: SysTime) -> None:
        """Public flush: run the pending resolve without draining objects
        (array-drain consumers pair this with take_order_arrays)."""
        self._last_time = time
        self._flush(time)

    def take_order_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """(src, seq) of executed dots in execution order since the last
        take; requires ``record_order_arrays``."""
        assert self.record_order_arrays
        if not self._order_arrays:
            empty = np.empty(0, np.int64)
            return empty, empty
        chunks, self._order_arrays = self._order_arrays, []
        return (
            np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]),
        )

    def _emit_rows(self, rows: np.ndarray, src, seq, tms, time: SysTime) -> None:
        if self.record_order_arrays:
            # array-native consumer: the execution order leaves as columns;
            # materializing (and never draining) the object mirror would
            # both leak and defeat the feature
            self._order_arrays.append((src[rows], seq[rows]))
        else:
            cmds = self._backlog.cmds
            # map + tolist: ~3x faster than a genexpr with ndarray indices
            # at 250k rows (list.__getitem__ on ints, one C-level loop)
            self._to_execute.extend(map(cmds.__getitem__, rows.tolist()))
        self._frontier.add_batch(src[rows], seq[rows])
        self._note_emitted(src[rows], seq[rows])
        now = float(time.millis())
        self._metrics.collect_many(
            ExecutorMetricsKind.EXECUTION_DELAY, np.maximum(now - tms[rows], 0.0)
        )

    def _resolve_stuck_rows(
        self, stuck_rows, src, seq, deps, tms, time: SysTime
    ) -> np.ndarray:
        """Host oracle over the stuck residue (dep-closed by the ``stuck``
        contract of resolve_general): rebuild the subgraph with deps
        restricted to stuck members (everything else the device either
        emitted before them or left missing-blocked — and missing-blocked
        rows are never stuck) and run it to completion.  Prefers the
        native C++ resolver (fantoch_tpu/native, the Rust-Tarjan twin);
        falls back to the Python oracle when the toolchain is missing."""
        emitted = self._resolve_stuck_rows_native(
            stuck_rows, src, seq, deps, tms, time
        )
        if emitted is not None:
            return emitted
        return self._resolve_stuck_rows_python(
            stuck_rows, src, seq, deps, tms, time
        )

    def _resolve_stuck_rows_native(
        self, stuck_rows, src, seq, deps, tms, time: SysTime
    ) -> Optional[np.ndarray]:
        from fantoch_tpu import native

        if not native.available():
            return None
        stuck_rows = np.asarray(stuck_rows, dtype=np.int64)
        n = len(stuck_rows)
        packed = pack_dots(src[stuck_rows], seq[stuck_rows])
        slot_of = {int(p): i for i, p in enumerate(packed)}
        # CSR restricted to stuck members (TERMINAL outside — emitted or
        # missing-blocked rows never appear in a stuck residue)
        row_targets: List[List[int]] = []
        for i in stuck_rows:
            row_targets.append(
                [slot_of[int(p)] for p in deps[int(i)] if int(p) in slot_of]
            )
        offsets = np.zeros(n + 1, dtype=np.int32)
        offsets[1:] = np.cumsum([len(t) for t in row_targets])
        targets = np.fromiter(
            (t for row in row_targets for t in row), np.int32, offsets[-1]
        )
        out = native.resolve_sccs(offsets, targets, packed)
        if out is None:
            return None
        order, sizes = out
        assert len(order) == n, (
            f"stuck residue not fully resolvable: {len(order)}/{n}"
        )
        rows = stuck_rows[order]
        self._emit_rows(rows, src, seq, tms, time)
        # one CHAIN_SIZE sample per SCC: block boundaries every `size` rows
        pos = 0
        scc_sizes = []
        while pos < n:
            scc_sizes.append(int(sizes[pos]))
            pos += int(sizes[pos])
        self._metrics.collect_many(ExecutorMetricsKind.CHAIN_SIZE, scc_sizes)
        return rows

    def _resolve_stuck_rows_python(
        self, stuck_rows, src, seq, deps, tms, time: SysTime
    ) -> np.ndarray:
        from fantoch_tpu.protocol.common.graph_deps import Dependency

        stuck_set = {
            (int(src[i]) << 32) | int(seq[i]): int(i) for i in stuck_rows
        }
        oracle = DependencyGraph(self._process_id, self._shard_id, self._config)
        shards = frozenset({self._shard_id})
        cmds = self._backlog.cmds
        emitted_rows: List[int] = []
        row_of = {id(cmds[int(i)]): int(i) for i in stuck_rows}
        for i in stuck_rows:
            i = int(i)
            dot = Dot(int(src[i]), int(seq[i]))
            dep_list = [
                Dependency(Dot(int(p >> 32), int(p & 0xFFFFFFFF)), shards)
                for p in deps[i]
                if int(p) in stuck_set
            ]
            oracle.handle_add(dot, cmds[i], dep_list, time)
            for done in oracle.commands_to_execute():
                r = row_of[id(done)]
                emitted_rows.append(r)
                self._metrics.collect(
                    ExecutorMetricsKind.EXECUTION_DELAY,
                    max(int(time.millis() - tms[r]), 0),
                )
                if self.record_order_arrays:
                    self._order_arrays.append(
                        (src[r : r + 1], seq[r : r + 1])
                    )
                else:
                    self._to_execute.append(done)
        chain_hist = oracle.metrics().get_collected(ExecutorMetricsKind.CHAIN_SIZE)
        if chain_hist is not None:
            from fantoch_tpu.core.metrics import Histogram

            self._metrics.collected.setdefault(
                ExecutorMetricsKind.CHAIN_SIZE, Histogram()
            ).merge(chain_hist)
        rows = np.array(emitted_rows, dtype=np.int64)
        if len(rows):
            self._frontier.add_batch(src[rows], seq[rows])
            self._note_emitted(src[rows], seq[rows])
        assert len(rows) == len(stuck_rows), (
            f"stuck residue not fully resolvable: {len(rows)}/{len(stuck_rows)}"
        )
        return rows


def _close_stuck_set(
    stuck_rows: np.ndarray, dep_rows: np.ndarray, remaining_mask: np.ndarray
) -> np.ndarray:
    """Enforce the stuck-residue contract before the host oracle runs: a
    row may only enter the oracle if every in-batch dependency is emitted
    or itself in the stuck set.  ``resolve_general``'s iteration budget can
    misclassify rows as stuck when a *missing* dependency lies deeper than
    its propagation horizon (merge vertices advance it one hop per round);
    the oracle drops out-of-set deps as satisfied, so an unclosed set would
    execute commands whose dependencies never committed.  Rows filtered
    out here simply stay in the backlog for a later resolve."""
    from collections import deque as _deque

    batch, _width = dep_rows.shape
    in_set = np.zeros(batch, dtype=bool)
    in_set[stuck_rows] = True
    emitted = ~remaining_mask
    valid = dep_rows >= 0
    safe = np.clip(dep_rows, 0, batch - 1)
    # seed disqualifiers: a MISSING slot, or a dep that is neither emitted
    # nor in the set (one vectorized pass; the common case — a genuinely
    # closed cycle residue — returns here)
    slot_ok = np.where(valid, emitted[safe] | in_set[safe], dep_rows != MISSING)
    bad = in_set & ~slot_ok.all(axis=1)
    if not bad.any():
        return np.asarray(stuck_rows)
    # O(edges) reverse-worklist: removal propagates to in-set dependents
    rev: dict = {}
    for r in np.asarray(stuck_rows).tolist():
        for d in dep_rows[r]:
            d = int(d)
            if d >= 0 and in_set[d]:
                rev.setdefault(d, []).append(r)
    removed = bad
    work = _deque(np.nonzero(bad)[0].tolist())
    while work:
        r = work.popleft()
        for dependent in rev.get(r, ()):
            if not removed[dependent]:
                removed[dependent] = True
                work.append(dependent)
    return np.nonzero(in_set & ~removed)[0]


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
