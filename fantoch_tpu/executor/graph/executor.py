"""GraphExecutor: the executor wrapper around DependencyGraph.

Reference: fantoch_ps/src/executor/graph/executor.rs.  Two-executor split:
the main executor (index 0) orders and executes commands; the secondary
(index 1) answers remote dependency requests and absorbs Executed
broadcasts — so cross-shard request serving never blocks ordering.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, List, Optional, Set, Tuple

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.kvs import KVStore
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import Executor, ExecutorResult
from fantoch_tpu.executor.graph.deps_graph import DependencyGraph, RequestReply
from fantoch_tpu.protocol.common.graph_deps import Dependency


# --- execution info variants (executor.rs:205-222) ---


@dataclass
class GraphAdd:
    dot: Dot
    cmd: Command
    deps: Set[Dependency]


@dataclass
class GraphAddBatch:
    """A whole commit buffer crossing the Protocol/Executor boundary as
    arrays (VERDICT r2 item 2; single-shard only — multi-shard commits keep
    per-command GraphAdd with full Dependency shard sets).

    ``dep_dots`` is int64[B, W] of packed dependency dots
    (fantoch_tpu/ops/frontier.py pack_dots), -1 padded."""

    dot_src: "np.ndarray"
    dot_seq: "np.ndarray"
    key: "np.ndarray"  # int32 conflict-key hash, -1 = multi-key
    dep_dots: "np.ndarray"
    cmds: List[Command]


@dataclass
class GraphNoop:
    """A dot committed as a recovered noop (protocol/recovery.py): nothing
    executes — the dot just counts as executed so dependents waiting on it
    resolve (the same seam RequestReplyExecuted uses)."""

    dot: Dot


@dataclass
class GraphRequest:
    from_shard: ShardId
    dots: Set[Dot]


@dataclass
class GraphRequestReply:
    infos: List[RequestReply]


@dataclass
class GraphExecuted:
    dots: Set[Dot]


GraphExecutionInfo = object  # union of the above

_MAIN_EXECUTOR_INDEX = 0
_SECONDARY_EXECUTOR_INDEX = 1


class GraphExecutor(Executor):
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config,
                 graph_cls: type | None = None):
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        if graph_cls is None:
            if config.batched_graph_executor:
                from fantoch_tpu.executor.graph.batched import (
                    BatchedDependencyGraph,
                )

                graph_cls = BatchedDependencyGraph
            else:
                graph_cls = DependencyGraph
        self.graph = graph_cls(process_id, shard_id, config)
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._to_clients: Deque[ExecutorResult] = deque()
        self._to_executors: List[Tuple[ShardId, GraphExecutionInfo]] = []
        # tracing: which handle_batch drain resolved each traced command
        self._trace_batch = 0

    def set_executor_index(self, index: int) -> None:
        self.graph.executor_index = index

    def share_state_from(self, primary: "GraphExecutor") -> None:
        """Share the primary executor's vertex index (the reference's
        SharedMap, index.rs:19-22): the secondary request-serving executor
        must see the main executor's *pending* vertices — answering peer
        shards only for executed dots deadlocks cross-shard dependency
        cycles (each shard waits for the others to execute first).  Safe
        without locks: one asyncio loop, no preemption inside a handler."""
        self.graph.share_vertex_index(primary.graph)

    def cleanup(self, time: SysTime) -> None:
        if self._config.shard_count > 1:
            self.graph.cleanup(time)
            self._fetch_actions(time)

    def monitor_pending(self, time: SysTime):
        """Liveness watchdog; returns the missing dependency dots (if any)
        so the runner can nudge the protocol's recovery plane."""
        return self.graph.monitor_pending(time)

    def handle_batch(self, infos, time: SysTime) -> None:
        """Group runs of GraphAdds into one batched graph add (a single
        device resolve with the batched resolver), preserving info order."""
        self._trace_batch += 1
        adds = []

        def flush():
            if adds:
                self.graph.handle_add_batch(adds, time)
                adds.clear()
                self._fetch_actions(time)

        for info in infos:
            if isinstance(info, GraphAdd) and not self._config.execute_at_commit:
                adds.append((info.dot, info.cmd, list(info.deps)))
            else:
                flush()
                self.handle(info, time)
        flush()

    def handle(self, info: GraphExecutionInfo, time: SysTime) -> None:
        if isinstance(info, GraphAdd):
            if self._config.execute_at_commit:
                self._execute(info.cmd)
            else:
                self.graph.handle_add(info.dot, info.cmd, list(info.deps), time)
                self._fetch_actions(time)
        elif isinstance(info, GraphAddBatch):
            if self._config.execute_at_commit:
                for cmd in info.cmds:
                    self._execute(cmd)
            elif getattr(self.graph, "_array_mode", False):
                self.graph.handle_add_arrays(
                    info.dot_src, info.dot_seq, info.key, info.dep_dots, info.cmds, time
                )
                self._fetch_actions(time)
            else:
                # host-oracle graph: unpack to per-command adds (buffered
                # batches are single-shard, so deps are local)
                shards = frozenset({self._shard_id})
                for i, cmd in enumerate(info.cmds):
                    deps = [
                        Dependency(Dot(int(p >> 32), int(p & 0xFFFFFFFF)), shards)
                        for p in info.dep_dots[i]
                        if p >= 0
                    ]
                    self.graph.handle_add(
                        Dot(int(info.dot_src[i]), int(info.dot_seq[i])), cmd, deps, time
                    )
                self._fetch_actions(time)
        elif isinstance(info, GraphNoop):
            # execute-at-commit has no ordering state to resolve
            if not self._config.execute_at_commit:
                self.graph.handle_noop(info.dot, time)
                self._fetch_actions(time)
        elif isinstance(info, GraphRequest):
            self.graph.handle_request(info.from_shard, info.dots, time)
            self._fetch_actions(time)
        elif isinstance(info, GraphRequestReply):
            self.graph.handle_request_reply(info.infos, time)
            self._fetch_actions(time)
        elif isinstance(info, GraphExecuted):
            self.graph.handle_executed(info.dots, time)
        else:
            raise AssertionError(f"unknown execution info {info}")

    def device_counters(self):
        """Per-dispatch tallies of the resident graph plane (None when
        the plane is off) — the same ``Executor.device_counters`` seam
        the table and pred planes feed, so ``bin/obs.py summarize``, the
        telemetry series, and the bench rows cover EPaxos/Atlas like
        Newt and Caesar."""
        plane = getattr(self.graph, "_plane", None)
        if plane is None:
            return None
        return {
            "graph_plane_dispatches": plane.dispatches,
            "graph_plane_grows": plane.grows,
            "graph_plane_new_rows": plane.stats["new_rows"],
            "graph_plane_update_capacity": plane.stats["update_capacity"],
            "graph_plane_patched_cells": plane.stats["patched_cells"],
            "graph_plane_residual_rows": plane.stats["residual_rows"],
            "graph_plane_compactions": plane.stats["compactions"],
            "graph_plane_kernel_ms": round(plane.stats["kernel_ms"], 3),
            # host->device backlog materializations: 1 lazy initial, +1
            # per compaction / live capacity-or-width grow, +1 per
            # restart-from-snapshot — never one per resolve (the
            # residency invariant; new-row deltas are the only steady-
            # state host->device traffic)
            "graph_plane_resident_uploads": plane.resident_uploads,
            # configuration gauge (max-folded, not summed)
            "graph_plane_slot_capacity": plane._cap,
            # accelerator fault tolerance: failover/rebuild tallies,
            # degraded wall, and the health gauge (max-folded)
            **{
                f"graph_plane_{k}": v
                for k, v in plane.fault_counters().items()
            },
        }

    def device_planes(self):
        plane = getattr(self.graph, "_plane", None)
        return (plane,) if plane is not None else ()

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    def to_executors(self) -> Optional[Tuple[ShardId, GraphExecutionInfo]]:
        return self._to_executors.pop() if self._to_executors else None

    def executed(self, time: SysTime):
        """Executed clock consumed by the protocol's GC (non-standard in the
        reference's GraphExecutor — EPaxos/Atlas GC is driven by MCommitDot
        instead; kept for parity with Executor API)."""
        return None

    @classmethod
    def parallel(cls) -> bool:
        return True

    def metrics(self) -> Metrics:
        return self.graph.metrics()

    def monitor(self):
        return self._store.monitor

    # --- internals (executor.rs:124-196) ---

    def _fetch_actions(self, time: SysTime) -> None:
        while True:
            cmd = self.graph.command_to_execute()
            if cmd is None:
                break
            self._execute(cmd)
        if self._config.shard_count > 1:
            added = self.graph.to_executors()
            if added:
                self._to_executors.append((self._shard_id, GraphExecuted(added)))
            for to_shard, dots in self.graph.requests().items():
                self._to_executors.append((to_shard, GraphRequest(self._shard_id, dots)))
            for to_shard, infos in self.graph.request_replies().items():
                self._to_executors.append((to_shard, GraphRequestReply(infos)))

    def _execute(self, cmd: Command) -> None:
        tracer = self.tracer
        if tracer.enabled:
            # "ready" = the graph resolved the command into an executable
            # position (stable SCC); "executed" = KVStore work done
            tracer.span(
                "ready", cmd.rifl, pid=self._process_id,
                meta={"batch": self._trace_batch},
            )
        self._to_clients.extend(cmd.execute(self._shard_id, self._store))
        if tracer.enabled:
            tracer.span("executed", cmd.rifl, pid=self._process_id)

    # --- executor routing (executor.rs:242-262) ---

    @staticmethod
    def executor_index_of(info: GraphExecutionInfo):
        if isinstance(info, (GraphAdd, GraphAddBatch, GraphNoop, GraphRequestReply)):
            return (0, _MAIN_EXECUTOR_INDEX)
        return (0, _SECONDARY_EXECUTOR_INDEX)
