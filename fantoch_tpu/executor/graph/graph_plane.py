"""Device-resident dependency backlog for the batched graph executor.

The host twin (:class:`~fantoch_tpu.executor.graph.batched.BatchedDependencyGraph`
with the plane off) keeps its backlog in host numpy columns and re-ships
the *entire* backlog through ``jnp.asarray`` on every resolve, then
blocks on the fetch.  This plane is the table/pred-plane move applied to
the graph executor — the last executor family still paying
upload-per-resolve (ROADMAP item 5's remainder): the dependency backlog
— src/seq/key columns plus the dep-slot matrix — lives ON DEVICE across
feeds as donated in-place state
(``ops/graph_resolve.resolve_graph_plane_step``), each executor feed is
ONE dispatch that installs the new rows, patches the ``MISSING`` cells
whose dots just committed (the waiter-index protocol of
``executor/pred_plane.py``), and re-resolves the whole pending window
with the same kernels the host-column path dispatches per flush
(``resolve_keyed_auto`` for single-key functional windows,
``resolve_general`` / ``resolve_general_resident`` otherwise).  Only the
emitted order comes back.

Residual protocol: a missing-blocked row (a dependency not committed
here yet) stays resident — its ``MISSING`` cells are patched when the
dep commits in a later feed (or resolves as a recovered noop), so
blocked rows never round-trip through host columns.

Host bookkeeping is COLUMN-NATIVE (the PR 4 arrays discipline): dots
are packed int64s, installs/emissions are vectorized numpy over the
feed, and the only per-item host work is one dict probe per dependency.
Slots are bump-allocated; when the window fills the plane compacts —
still-pending rows re-pack to the bottom (dep cells remapped through a
LUT, references to executed rows folding to ``TERMINAL``) in one
counted re-upload, with 3/4-capacity grow hysteresis so a few residual
rows cannot flap the compiled shape.  The full backlog state is also
HOST-MIRRORED (installs and patches are cheap numpy writes), so
compaction, the stuck-cycle host oracle, and the liveness watchdog
never fetch device state.

Pipelining: ``pipeline_depth`` K keeps up to K-1 dispatched rounds
un-fetched (the ``run/pipeline.py`` delivery-lag contract) so a serving
loop overlaps the next feed's host assembly with device compute; depth
1 (the default, and what executor pools use) is fully synchronous.
Host-side emission dedup makes drains idempotent, so the rare
stuck-cycle follow-up dispatch composes with in-flight rounds.

Buffer lifecycle — donation-safe uploads, lazy host-mirror
re-materialization after restore with exactly ONE counted re-upload,
pow2 capacity growth, per-dispatch counters — is the shared
:class:`~fantoch_tpu.executor.device_plane.DevicePlane` base.

Clock width: device dot sequences are int32; the plane refuses
sequences at or above ``2^31 - 1`` with the shared typed error.
"""

from __future__ import annotations

import os
import time as _time
from collections import deque
from typing import Deque, Dict, List, Set, Tuple

import numpy as np

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.errors import DeviceCorruptionError, DeviceFailedError
from fantoch_tpu.executor.base import ExecutorMetricsKind
from fantoch_tpu.executor.device_plane import DevicePlane, next_pow2 as _pow2
from fantoch_tpu.executor.table_plane import ClockOverflowError
from fantoch_tpu.ops.frontier import DeviceFrontier, pack_dots
from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL

_INT32_MAX = (1 << 31) - 1
_SEQ_MASK = (1 << 32) - 1

def graph_plane_enabled(config: Config) -> bool:
    """The plane routing switch: an explicit ``Config.device_graph_plane``
    beats the ``FANTOCH_GRAPH_PLANE`` env var beats the default (off —
    the host-column path stays the oracle twin)."""
    if config.device_graph_plane is not None:
        return bool(config.device_graph_plane)
    env = os.environ.get("FANTOCH_GRAPH_PLANE")
    if env is None or env == "":
        return False
    return env not in ("0", "false", "no")


class DeviceGraphPlane(DevicePlane):
    """Resident dependency backlog + one fused dispatch per executor
    feed.  Driven by :class:`BatchedDependencyGraph` behind
    ``Config.device_graph_plane`` (the host-column path is the oracle
    twin — per-key execution-order parity tested in
    tests/test_graph_plane.py)."""

    __slots__ = (
        "_process_id",
        "_shard_id",
        "_config",
        "_frontier",
        "_metrics",
        "_structure_threshold",
        "_width",
        "_next_slot",
        "_slot_of",
        "_slot_src",
        "_slot_seq",
        "_slot_key",
        "_slot_tms",
        "_slot_deps",
        "_slot_general",
        "_general_rows",
        "_exec_host",
        "_slot_cmd",
        "_waiters",
        "_waiter_since",
        "_patches",
        "_inflight",
        "_emitted",
        "pipeline_depth",
    )

    plane_name = "graph"

    def __init__(
        self,
        process_id: ProcessId,
        shard_id: ShardId,
        config: Config,
        frontier: DeviceFrontier,
        metrics: Metrics,
        *,
        structure_threshold: int = 4096,
        slot_capacity: int = 1024,
        width: int = 4,
    ):
        super().__init__(
            slot_capacity,
            stats={
                # per-dispatch tallies: new_rows/update_capacity is the
                # install-batch occupancy (padding waste), patched_cells
                # the waiter-index patches applied, residual_rows the
                # still-blocked window after the drain, kernel_ms the
                # dispatch->fetch wall; compactions counts window
                # re-packs (each is one counted re-upload)
                "new_rows": 0,
                "update_capacity": 0,
                "patched_cells": 0,
                "residual_rows": 0,
                "compactions": 0,
                "kernel_ms": 0.0,
            },
        )
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        # the graph's executed frontier and metrics are SHARED (one
        # executed set, one histogram registry — pickle preserves the
        # sharing within one executor snapshot)
        self._frontier = frontier
        self._metrics = metrics
        self._structure_threshold = structure_threshold
        self._width = _pow2(max(width, 1))
        self._next_slot = 0
        # packed dot -> slot, PENDING rows only (emission pops)
        self._slot_of: Dict[int, int] = {}
        # host mirrors of the resident columns (installs/patches are
        # cheap numpy writes, so compaction/oracle/watchdog never fetch)
        self._slot_src = np.zeros(self._cap, dtype=np.int64)
        self._slot_seq = np.zeros(self._cap, dtype=np.int64)
        self._slot_key = np.full(self._cap, -1, dtype=np.int32)
        self._slot_tms = np.zeros(self._cap, dtype=np.float64)
        self._slot_deps = np.full(
            (self._cap, self._width), TERMINAL, dtype=np.int32
        )
        # rows that disqualify the keyed kernel (multi-key, or >1 live
        # dep at install); the counter gates the per-dispatch mode
        self._slot_general = np.zeros(self._cap, dtype=bool)
        self._general_rows = 0
        self._exec_host = np.zeros(self._cap, dtype=bool)
        self._slot_cmd: Dict[int, object] = {}
        # missing packed dot -> [(slot, col), ...] cells awaiting it,
        # with first-registration time (the watchdog only nudges dots
        # missing past the pending threshold)
        self._waiters: Dict[int, List[Tuple[int, int]]] = {}
        self._waiter_since: Dict[int, float] = {}
        # dep patches buffered between dispatches (noop resolutions land
        # here; arrival patches are generated at feed time)
        self._patches: List[Tuple[int, int, int]] = []
        # in-flight dispatch tokens: (mode, step output, U, ucap, P,
        # time, t0) — up to pipeline_depth - 1 stay un-fetched
        self._inflight: Deque[tuple] = deque()
        # drained emissions awaiting the graph: (cmds, src, seq) chunks
        self._emitted: List[Tuple[list, np.ndarray, np.ndarray]] = []
        self.pipeline_depth = 1

    # --- feed surface (BatchedDependencyGraph drives this) ---

    @property
    def pending_count(self) -> int:
        """Resident rows still blocked (committed, not yet executed)."""
        return len(self._slot_of)

    @property
    def in_flight(self) -> int:
        return len(self._inflight)

    @property
    def has_patches(self) -> bool:
        return bool(self._patches)

    def reserve(self, capacity: int) -> None:
        """Pre-size the slot window (bench/serving loops: a capacity that
        covers the whole run keeps ``resident_uploads`` at exactly 1 —
        no compaction re-uploads).  Only before the first install."""
        assert self._next_slot == 0 and self._resident is None
        while self._cap < _pow2(capacity):
            self._grow_columns()

    def feed(
        self,
        dot_src: np.ndarray,  # int64[B]
        dot_seq: np.ndarray,  # int64[B]
        key: np.ndarray,  # int32[B] conflict-key hash (-1 = multi-key)
        tms: np.ndarray,  # float64[B] commit time (ms)
        dep_dots: np.ndarray,  # int64[B, W] packed dep dots, -1 pad
        cmds: List[object],
        time: SysTime,
    ) -> None:
        """Install one column feed and dispatch the resident resolve."""
        B = len(dot_src)
        if B == 0:
            return self.flush(time)
        if int(dot_seq.max()) >= _INT32_MAX:
            raise ClockOverflowError(
                "dot sequence >= 2^31 - 1: the device graph plane is "
                "31-bit windowed (disable device_graph_plane)"
            )
        now = float(time.millis()) if time is not None else 0.0
        self._make_room(B)
        packed = pack_dots(dot_src, dot_seq)
        packed_list = packed.tolist()
        slot_of = self._slot_of
        # exactly-once: a dot may be neither resident, nor executed, nor
        # repeated within the feed itself (the host twin's duplicate-dot
        # assert, extended across feeds)
        assert len(set(packed_list)) == B, "duplicate dot added"
        for pd in packed_list:
            assert pd not in slot_of, "duplicate dot added"
        assert not self._frontier.contains_batch(dot_src, dot_seq).any(), (
            "duplicate dot added"
        )

        # bump-allocate contiguous slots for the whole feed
        base = self._next_slot
        self._next_slot = base + B
        slots = np.arange(base, base + B, dtype=np.int64)
        slot_of.update(zip(packed_list, range(base, base + B)))
        self._slot_src[base : base + B] = dot_src
        self._slot_seq[base : base + B] = dot_seq
        self._slot_key[base : base + B] = key
        self._slot_tms[base : base + B] = tms
        self._exec_host[base : base + B] = False
        self._slot_cmd.update(zip(range(base, base + B), cmds))

        # --- dependency encode (vectorized; one dict probe per dep) ---
        valid = (dep_dots >= 0) & (dep_dots != packed[:, None])  # self-deps drop
        r_idx, c_src = np.nonzero(valid)
        if len(r_idx):
            v = dep_dots[r_idx, c_src]
            vals = np.empty(len(v), dtype=np.int64)
            miss_at: List[int] = []
            for e, pd in enumerate(v.tolist()):
                s = slot_of.get(pd)
                if s is not None:
                    vals[e] = s
                else:
                    miss_at.append(e)
            if miss_at:
                mp = np.asarray(miss_at, dtype=np.int64)
                mv = v[mp]
                # not in the window: executed -> TERMINAL, else MISSING
                # (one vectorized frontier probe for the whole feed)
                ex = self._frontier.contains_batch(
                    mv >> 32, mv & _SEQ_MASK
                )
                vals[mp] = np.where(ex, TERMINAL, MISSING)
            # already-satisfied cells (executed deps) encode to nothing:
            # only live cells occupy dep columns, so steady-state serving
            # feeds (most deps executed at install) never widen the window
            keep = vals != TERMINAL
            r_idx, v, vals = r_idx[keep], v[keep], vals[keep]
            live_cnt = np.bincount(r_idx, minlength=B)
            width_needed = int(live_cnt.max()) if len(r_idx) else 0
            self._ensure_width(max(width_needed, 1))
            u_deps = np.full((B, self._width), TERMINAL, dtype=np.int32)
            head = np.r_[True, r_idx[1:] != r_idx[:-1]] if len(r_idx) else (
                np.zeros(0, dtype=bool)
            )
            iota = np.arange(len(r_idx), dtype=np.int64)
            cols = iota - np.maximum.accumulate(np.where(head, iota, 0))
            u_deps[r_idx, cols] = vals
            for e in np.nonzero(vals == MISSING)[0].tolist():
                pd = int(v[e])
                w_slot, w_col = int(base + r_idx[e]), int(cols[e])
                self._waiters.setdefault(pd, []).append((w_slot, w_col))
                self._waiter_since.setdefault(pd, now)
        else:
            live_cnt = np.zeros(B, dtype=np.int64)
            self._ensure_width(1)
            u_deps = np.full((B, self._width), TERMINAL, dtype=np.int32)
        self._slot_deps[base : base + B] = u_deps
        gen = (key < 0) | (live_cnt > 1)
        self._slot_general[base : base + B] = gen
        self._general_rows += int(gen.sum())

        # the residual re-feed: earlier rows waiting on this feed's dots
        # get their MISSING cells patched to the new slots
        if self._waiters:
            for pd, slot in zip(packed_list, range(base, base + B)):
                cells = self._waiters.pop(pd, None)
                if cells is None:
                    continue
                self._waiter_since.pop(pd, None)
                for w_slot, w_col in cells:
                    self._patches.append((w_slot, w_col, slot))
                    self._slot_deps[w_slot, w_col] = slot

        self._dispatch(
            slots,
            u_deps,
            key.astype(np.int32, copy=False),
            dot_src.astype(np.int32),
            dot_seq.astype(np.int32),
            time,
        )

    def note_noop(self, source: int, sequence: int) -> None:
        """A recovery-committed noop: the dot counts as executed (the
        graph adds it to the shared frontier), and every cell waiting on
        it resolves to TERMINAL on the next dispatch."""
        pd = (int(source) << 32) | int(sequence)
        assert pd not in self._slot_of, "a noop dot has no resident slot"
        self._waiter_since.pop(pd, None)
        for w_slot, w_col in self._waiters.pop(pd, ()):
            self._patches.append((w_slot, w_col, TERMINAL))
            self._slot_deps[w_slot, w_col] = TERMINAL

    def flush(self, time: SysTime) -> None:
        """Dispatch any buffered patches (noop resolutions with no new
        feed) and drain per the pipeline depth (end-of-stream tails are
        ``drain_all`` / the graph's ``flush_plane_pipeline``)."""
        if self._patches:
            empty = np.empty(0, dtype=np.int64)
            self._dispatch(
                empty,
                np.empty((0, self._width), dtype=np.int32),
                empty.astype(np.int32),
                empty.astype(np.int32),
                empty.astype(np.int32),
                time,
            )
        while len(self._inflight) > max(self.pipeline_depth - 1, 0):
            self._drain_one()

    def drain_all(self) -> None:
        while self._inflight:
            self._drain_one()

    def take_emitted(self) -> List[Tuple[list, np.ndarray, np.ndarray]]:
        """Drained (cmds, src, seq) emission chunks in execution order
        since the last take (the graph routes them to the object drain
        or the order-arrays drain)."""
        out, self._emitted = self._emitted, []
        return out

    # --- the resident dispatch ---

    def _mode(self) -> str:
        """Single-key functional windows ride the sort-based keyed kernel
        (no exact-structure entry — the plane reports aggregate counters;
        the host-column twin keeps the CHAIN_SIZE path); multi-key /
        multi-dep windows ride ``resolve_general`` below the kernel-size
        gate (mutual cycles collapse on device, exact structure) and the
        resident peel-and-compact schedule above it."""
        if self._general_rows > 0:
            if self._cap <= self._structure_threshold:
                return "general"
            return "general_resident"
        return "keyed"

    def _dispatch(self, slots, u_deps, u_key, u_src, u_seq, time) -> None:
        patches, self._patches = self._patches, []
        U, P = len(slots), len(patches)
        if U == 0 and P == 0:
            return
        out, mode, t0, ucap = self._dispatch_raw(
            slots, u_deps, u_key, u_src, u_seq, patches, (), time=time
        )
        self._inflight.append((mode, out, U, ucap, P, time, t0))
        while len(self._inflight) > max(self.pipeline_depth - 1, 0):
            self._drain_one()

    def _pad_columns(self, slots, u_deps, u_key, u_src, u_seq, patches, marks):
        """The padded kernel columns for one dispatch — shared by the
        resident dispatch and the host twin's stuck follow-ups, so both
        feed the kernel bit-identical inputs."""
        cap = self._cap
        U, P, E = len(slots), len(patches), len(marks)
        # pad to pow2 FLOORS so the common serving shapes share compiled
        # programs: per-dispatch install/patch counts jitter, and every
        # distinct shape is a fresh XLA program (~minutes on small rigs)
        ucap = _pow2(max(U, 64))
        pcap = _pow2(max(P, 64))
        ecap = _pow2(max(E, 8))
        u_row = np.full(ucap, cap, dtype=np.int32)  # pad -> dropped
        u_dep = np.full((ucap, self._width), TERMINAL, dtype=np.int32)
        u_k = np.zeros(ucap, dtype=np.int32)
        u_s = np.zeros(ucap, dtype=np.int32)
        u_q = np.zeros(ucap, dtype=np.int32)
        if U:
            u_row[:U] = slots
            u_dep[:U] = u_deps
            u_k[:U] = u_key
            u_s[:U] = u_src
            u_q[:U] = u_seq
        p_row = np.full(pcap, cap, dtype=np.int32)  # pad -> dropped
        p_col = np.zeros(pcap, dtype=np.int32)
        p_val = np.zeros(pcap, dtype=np.int32)
        for i, (slot, col, val) in enumerate(patches):
            p_row[i], p_col[i], p_val[i] = slot, col, val
        e_row = np.full(ecap, cap, dtype=np.int32)  # pad -> dropped
        if E:
            e_row[:E] = marks
        return (u_row, u_dep, u_k, u_s, u_q, p_row, p_col, p_val, e_row), ucap

    def _dispatch_raw(
        self, slots, u_deps, u_key, u_src, u_seq, patches, marks, time=None
    ):
        import jax.numpy as jnp

        from fantoch_tpu.ops.graph_resolve import resolve_graph_plane_step

        cols, ucap = self._pad_columns(
            slots, u_deps, u_key, u_src, u_seq, patches, marks
        )
        mode = self._mode()
        # every dispatch — primary AND stuck follow-up — is logged with
        # its mode, so the twin replays the identical kernel sequence and
        # tracks the resident state bit-for-bit (armed-only no-op)
        self._twin_note((mode, time) + cols)
        t0 = _time.perf_counter()
        if self.degraded:
            # served from the twin at this round's drain (out=None token)
            return None, mode, t0, ucap
        try:
            fault = self._fault_check_pre()
            self._materialize()
            out = resolve_graph_plane_step(
                *self._resident,
                *(jnp.asarray(c) for c in cols),
                mode=mode,
            )
            self._resident = tuple(out[:6])
            if fault is not None:
                self._poison_resident(fault)
            return out, mode, t0, ucap
        except (DeviceFailedError, DeviceCorruptionError) as exc:
            # dispatch-time failure (injected hang/raise): the round — and
            # every in-flight round, whose device results are no longer
            # trusted — is served from the twin at its drain
            self._device_failure(exc)
            self._fail_inflight()
            self._note_degraded(t0)
            return None, mode, t0, ucap

    def _fail_inflight(self) -> None:
        """Invalidate the device results of every in-flight round after a
        failure: their rows replay from the twin log (emission dedup makes
        the replay exactly-once), the drains just count them."""
        if self._inflight:
            self._inflight = deque(
                (m, None, u, uc, p, tm, tt)
                for (m, _o, u, uc, p, tm, tt) in self._inflight
            )

    # --- host twin (accelerator fault tolerance; DevicePlane base) ---

    def _twin_replay(self, state, entry):
        """One logged dispatch replayed statelessly through the SAME
        kernel (fresh ``jnp.array`` uploads — the donation-safety rule),
        with host emission performed HERE: emission dedup
        (``_exec_host``) makes rounds the device already drained replay
        as no-ops, while in-flight rounds at pipeline depth K emit
        exactly once, in round order — the depth-K exactly-once replay.
        Degraded serving has no device follow-ups, so stuck residues
        resolve on the twin itself (healthy folds see them already
        emitted and skip — the device's own follow-up was logged)."""
        mode, time = entry[0], entry[1]
        state, fetched = self._twin_step(state, mode, entry[2:])
        order, newly, stuck, leader = fetched
        self._emit(order[newly[order]], leader, time)
        while stuck is not None:
            stuck_slots = np.nonzero(stuck & ~self._exec_host)[0]
            if not len(stuck_slots):
                break
            closed = self._close_stuck(stuck_slots)
            if not len(closed):
                break
            self._stuck_oracle(closed, time)
            empty = np.empty(0, dtype=np.int64)
            mcols, _ucap = self._pad_columns(
                empty, np.empty((0, self._width), np.int32),
                empty.astype(np.int32), empty.astype(np.int32),
                empty.astype(np.int32), (), closed,
            )
            state, fetched = self._twin_step(state, self._mode(), mcols)
            order, newly, stuck, leader = fetched
            self._emit(order[newly[order]], leader, time)
        return state, fetched

    def _twin_step(self, state, mode, cols):
        """One kernel run on host-owned twin state; returns the new
        state and the per-mode result columns, all host numpy."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.graph_resolve import resolve_graph_plane_step

        out = resolve_graph_plane_step(
            *(jnp.array(a) for a in state),
            *(jnp.asarray(c) for c in cols),
            mode=mode,
        )
        new_state = tuple(np.asarray(a) for a in jax.device_get(out[:6]))
        if mode == "keyed":
            order, newly = jax.device_get((out.order, out.newly))
            fetched = (np.asarray(order), np.asarray(newly), None, None)
        else:
            order, newly, stuck, leader = jax.device_get(
                (out.order, out.newly, out.stuck, out.leader)
            )
            fetched = (
                np.asarray(order),
                np.asarray(newly),
                np.asarray(stuck),
                np.asarray(leader) if mode == "general" else None,
            )
        return new_state, fetched

    def _fetch_result(self, mode: str, out):
        """One blocking transfer for a dispatch's small result columns
        (the backlog state itself never round-trips)."""
        import jax

        if mode == "keyed":
            order, newly = jax.device_get((out.order, out.newly))
            return np.asarray(order), np.asarray(newly), None, None
        order, newly, stuck, leader = jax.device_get(
            (out.order, out.newly, out.stuck, out.leader)
        )
        leader_np = np.asarray(leader) if mode == "general" else None
        return np.asarray(order), np.asarray(newly), np.asarray(stuck), leader_np

    def _drain_one(self) -> None:
        mode, out, U, ucap, P, time, t0 = self._inflight.popleft()
        if out is None:
            # the round is (or already was, by an earlier fold) served
            # bit-for-bit from the twin — emission dedup makes rounds an
            # earlier fold replayed pure no-ops here
            self._twin_fold()
            self._note_degraded(t0)
        else:
            try:
                order, newly, stuck, leader = self._fetch_result(mode, out)
                self._check_deadline(t0)
                live_stuck = stuck is not None and bool(
                    (stuck & ~self._exec_host).any()
                )
                if (
                    not self._inflight
                    and not live_stuck
                    and self._shadow_sampled()
                ):
                    # serve the round from the twin FIRST (the device
                    # emission below dedups to a no-op), then verify the
                    # device state against it — a corrupt ``newly`` never
                    # reaches the host bookkeeping.  Rounds with live
                    # stuck residues defer to the next sampled round (the
                    # follow-up dispatch below would race the compare).
                    self._twin_fold()
                    self._shadow_compare(self._fetch_state())
                self._emit(order[newly[order]], leader, time)
                # stuck residues (general modes: 3+-cycles the device
                # pass cannot collapse) finish on the host Tarjan oracle;
                # a follow-up dispatch marks them executed on device and
                # resolves dependents
                while stuck is not None:
                    stuck_slots = np.nonzero(stuck & ~self._exec_host)[0]
                    if not len(stuck_slots):
                        break
                    closed = self._close_stuck(stuck_slots)
                    if not len(closed):
                        break  # budget misclassification: wait for a later feed
                    self._stuck_oracle(closed, time)
                    empty = np.empty(0, dtype=np.int64)
                    out2, mode2, _t0b, _ucap2 = self._dispatch_raw(
                        empty, np.empty((0, self._width), np.int32),
                        empty.astype(np.int32), empty.astype(np.int32),
                        empty.astype(np.int32), (), closed, time=time,
                    )
                    if out2 is None:
                        # the follow-up itself hit the injected fault:
                        # its marks entry replays through the twin
                        self._twin_fold()
                        break
                    order, newly, stuck, leader = self._fetch_result(
                        mode2, out2
                    )
                    self._emit(order[newly[order]], leader, time)
            except (DeviceFailedError, DeviceCorruptionError) as exc:
                # serve this round — and everything still logged — from
                # the twin; in-flight device results are dropped
                self._twin_fold()
                self._device_failure(exc)
                self._fail_inflight()
                self._note_degraded(t0)
        self._count_dispatch(
            t0,
            new_rows=U,
            update_capacity=ucap,
            patched_cells=P,
            residual_rows=self.pending_count,
        )
        # cutback: once the fault window closed, ONE counted re-upload of
        # the folded twin state (no-op unless failed)
        self._maybe_rebuild()

    def _emit(self, slots: np.ndarray, leader, time) -> None:
        """Host bookkeeping for one drain's executed slots, in emission
        order.  Idempotent (already-executed slots are dropped) so the
        stuck-cycle follow-up composes with in-flight rounds."""
        slots = np.asarray(slots, dtype=np.int64)
        if len(slots):
            slots = slots[~self._exec_host[slots]]
        if not len(slots):
            return
        self._exec_host[slots] = True
        src = self._slot_src[slots]
        seq = self._slot_seq[slots]
        cmds = self._slot_cmd
        emitted_cmds = [cmds.pop(s) for s in slots.tolist()]
        slot_of = self._slot_of
        for pd in pack_dots(src, seq).tolist():
            del slot_of[pd]
        self._general_rows -= int(self._slot_general[slots].sum())
        self._frontier.add_batch(src, seq)
        if time is not None:
            now = float(time.millis())
            self._metrics.collect_many(
                ExecutorMetricsKind.EXECUTION_DELAY,
                np.maximum(now - self._slot_tms[slots], 0.0),
            )
        if leader is not None:
            # exact per-SCC structure (structure modes only — the same
            # gating as the host-column path's want_structure)
            leaders = leader[slots]
            sizes = np.diff(
                np.concatenate(
                    [[0], np.nonzero(np.diff(leaders))[0] + 1, [len(slots)]]
                )
            )
            self._metrics.collect_many(ExecutorMetricsKind.CHAIN_SIZE, sizes)
        self._emitted.append((emitted_cmds, src, seq))

    # --- stuck-cycle host oracle (slot space) ---

    def _folded_deps(self) -> np.ndarray:
        """The host mirror of the dep matrix with cells on executed
        slots folded to TERMINAL — what the device's resolve sees."""
        deps = self._slot_deps
        live = deps >= 0
        safe = np.clip(deps, 0, self._cap - 1)
        return np.where(live & self._exec_host[safe], TERMINAL, deps)

    def _close_stuck(self, stuck_slots: np.ndarray) -> np.ndarray:
        from fantoch_tpu.executor.graph.batched import _close_stuck_set

        return np.asarray(
            _close_stuck_set(stuck_slots, self._folded_deps(), ~self._exec_host)
        )

    def _stuck_oracle(self, slots: np.ndarray, time) -> None:
        """Host Tarjan over the (dep-closed) stuck residue, restricted to
        stuck members — the host-column path's python oracle in slot
        space (stuck residues are rare 3+-cycles; the mirrors make the
        subgraph free to build)."""
        from fantoch_tpu.executor.graph.deps_graph import DependencyGraph
        from fantoch_tpu.protocol.common.graph_deps import Dependency

        in_set = set(slots.tolist())
        oracle = DependencyGraph(self._process_id, self._shard_id, self._config)
        shards = frozenset({self._shard_id})
        row_of = {id(self._slot_cmd[int(s)]): int(s) for s in slots}
        emitted_rows: List[int] = []
        for s in slots.tolist():
            dot = Dot(int(self._slot_src[s]), int(self._slot_seq[s]))
            dep_list = [
                Dependency(
                    Dot(int(self._slot_src[t]), int(self._slot_seq[t])), shards
                )
                for t in self._slot_deps[s].tolist()
                if t in in_set
            ]
            oracle.handle_add(dot, self._slot_cmd[s], dep_list, time)
            for done in oracle.commands_to_execute():
                emitted_rows.append(row_of[id(done)])
        assert len(emitted_rows) == len(slots), (
            f"stuck residue not fully resolvable: "
            f"{len(emitted_rows)}/{len(slots)}"
        )
        chain_hist = oracle.metrics().get_collected(ExecutorMetricsKind.CHAIN_SIZE)
        if chain_hist is not None:
            from fantoch_tpu.core.metrics import Histogram

            self._metrics.collected.setdefault(
                ExecutorMetricsKind.CHAIN_SIZE, Histogram()
            ).merge(chain_hist)
        self._emit(np.asarray(emitted_rows, dtype=np.int64), None, time)

    # --- capacity management ---

    def _make_room(self, need: int) -> None:
        """Ensure ``need`` contiguous bump slots: grow while the pending
        window could not fit at 3/4 capacity (growing a LIVE window
        recompiles the step program — the hysteresis keeps a few residual
        rows from flapping the capacity), then compact (re-pack pending
        rows to the bottom — same compiled shape, one counted re-upload)
        when the bump pointer is exhausted anyway."""
        if (
            len(self._slot_of) + need > (3 * self._cap) // 4
            or self._next_slot + need > self._cap
        ):
            # both paths renumber or reshape: retire in-flight rounds
            self.drain_all()
        while len(self._slot_of) + need > (3 * self._cap) // 4:
            self._grow_columns()
        if self._next_slot + need > self._cap:
            self._compact()

    def _grow_columns(self) -> None:
        old_cap = self._cap
        self._grow()  # doubles _cap; re-pads resident state when live
        for name in ("_slot_src", "_slot_seq", "_slot_tms"):
            old = getattr(self, name)
            grown = np.zeros(self._cap, dtype=old.dtype)
            grown[:old_cap] = old
            setattr(self, name, grown)
        key = np.full(self._cap, -1, dtype=np.int32)
        key[:old_cap] = self._slot_key
        self._slot_key = key
        deps = np.full((self._cap, self._width), TERMINAL, dtype=np.int32)
        deps[:old_cap] = self._slot_deps
        self._slot_deps = deps
        for name in ("_slot_general", "_exec_host"):
            old = getattr(self, name)
            grown = np.zeros(self._cap, dtype=bool)
            grown[:old_cap] = old
            setattr(self, name, grown)

    def _ensure_width(self, width: int) -> None:
        if width <= self._width:
            return
        self.drain_all()
        if self._fault_armed and self._twin_log:
            # entries logged at the old width cannot replay against the
            # widened twin — fold them out first (emission dedup makes
            # the healthy-path replays no-ops)
            self._twin_fold()
        new_w = _pow2(width)
        deps = np.full((self._cap, new_w), TERMINAL, dtype=np.int32)
        deps[:, : self._width] = self._slot_deps
        self._slot_deps = deps
        self._width = new_w
        state = self._rebuild_state()
        if self._resident is not None:
            self._upload(state)
        elif self._host_mirror is not None:
            self._host_mirror = state
        if self._twin_state is not None:
            self._twin_resync(state)
        self.grows += 1

    def _compact(self) -> None:
        """Re-pack the pending window to the bottom of the slot space
        from the HOST MIRRORS (no device fetch): dep cells remap through
        a LUT, references to executed rows fold to TERMINAL, one counted
        re-upload."""
        assert not self._inflight
        if self._fault_armed and self._twin_log:
            # entries describe the pre-compaction slot layout: fold them
            # before the renumbering (healthy replays dedup to no-ops)
            self._twin_fold()
        cap = self._cap
        old = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        old.sort()  # stable re-pack keeps slot order deterministic
        P = len(old)
        lut = np.full(cap, TERMINAL, dtype=np.int32)
        lut[old] = np.arange(P, dtype=np.int32)
        nd = self._slot_deps[old]
        live = nd >= 0
        safe = np.clip(nd, 0, cap - 1)
        nd = np.where(
            live,
            np.where(self._exec_host[safe], TERMINAL, lut[safe]),
            nd,
        ).astype(np.int32)
        # host columns follow the same re-pack
        self._slot_src[:P] = self._slot_src[old]
        self._slot_seq[:P] = self._slot_seq[old]
        self._slot_key[:P] = self._slot_key[old]
        self._slot_tms[:P] = self._slot_tms[old]
        self._slot_deps[:P] = nd
        self._slot_deps[P:] = TERMINAL
        self._slot_general[:P] = self._slot_general[old]
        self._slot_general[P:] = False
        self._general_rows = int(self._slot_general[:P].sum())
        self._exec_host[:] = False
        cmds = {int(lut[s]): self._slot_cmd[int(s)] for s in old.tolist()}
        self._slot_cmd.clear()
        self._slot_cmd.update(cmds)
        pend_pd = pack_dots(self._slot_src[:P], self._slot_seq[:P])
        self._slot_of.clear()
        self._slot_of.update(zip(pend_pd.tolist(), range(P)))
        remapped = {
            pd: [(int(lut[s]), c) for s, c in cells]
            for pd, cells in self._waiters.items()
        }
        self._waiters.clear()
        self._waiters.update(remapped)
        self._patches = [
            (int(lut[s]), c, int(lut[v]) if v >= 0 else v)
            for s, c, v in self._patches
        ]
        self._next_slot = P
        state = self._rebuild_state()
        if self.degraded:
            # no upload while failed over: the compacted window becomes
            # the new twin state; cutback re-uploads it (ONE upload)
            pass
        elif self._resident is not None or self._host_mirror is None:
            self._upload(state)
        else:
            self._host_mirror = state
        if self._twin_state is not None:
            self._twin_resync(state)
        self.stats["compactions"] += 1

    def _rebuild_state(self) -> Tuple[np.ndarray, ...]:
        """Full device state from the host mirrors at the current
        capacity/width (compaction, width growth, restore)."""
        cap = self._cap
        occ = np.zeros(cap, dtype=bool)
        occ[: self._next_slot] = True
        return (
            self._slot_deps.copy(),
            self._slot_key.copy(),
            self._slot_src.astype(np.int32),
            self._slot_seq.astype(np.int32),
            occ,
            self._exec_host.copy(),
        )

    # --- liveness watchdog (the BatchedDependencyGraph contract) ---

    def monitor_pending(self, time: SysTime):
        """Per-row liveness check over the host mirrors: old pending
        rows must be *transitively* missing-blocked (panic otherwise — a
        lost execution), rows blocked on missing deps past
        ``Config.executor_pending_fail_ms`` raise the typed stall, and
        the overdue missing dots are returned so the runner can nudge
        recovery.  A waiter dot found executed in the frontier is a lost
        wake and folds like an executed cell (its dependents then panic
        as pending-without-missing, exactly like the host twin)."""
        assert not self._inflight
        if not self._slot_of:
            return None
        from fantoch_tpu.executor.graph.indexes import (
            MONITOR_PENDING_THRESHOLD_MS,
        )

        now = float(time.millis())
        pend = np.fromiter(self._slot_of.values(), np.int64, len(self._slot_of))
        pending_for = now - self._slot_tms[pend]
        old_mask = pending_for >= MONITOR_PENDING_THRESHOLD_MS
        fail_ms = self._config.executor_pending_fail_ms
        ripe_mask = pending_for >= fail_ms if fail_ms is not None else None
        if not old_mask.any() and (ripe_mask is None or not ripe_mask.any()):
            return None
        # genuinely-missing frontier: waiter dots not executed; a waiter
        # dot IN the frontier is a lost wake — skipping it here leaves
        # its dependents without a missing set, so they trip the
        # pending-without-missing panic below
        row_missing: Dict[int, Set[Dot]] = {}
        if self._waiters:
            pds = np.fromiter(self._waiters.keys(), np.int64, len(self._waiters))
            executed = self._frontier.contains_batch(pds >> 32, pds & _SEQ_MASK)
            for pd, ex in zip(pds.tolist(), executed.tolist()):
                if ex:
                    continue
                dot = Dot(pd >> 32, pd & _SEQ_MASK)
                for slot, _col in self._waiters[pd]:
                    row_missing.setdefault(slot, set()).add(dot)
        cap = self._cap
        deps = self._folded_deps()
        direct = np.zeros(cap, dtype=bool)
        if row_missing:
            direct[np.fromiter(row_missing.keys(), np.int64)] = True
        nudge = {
            dot
            for slot in np.asarray(pend[old_mask]).tolist()
            for dot in row_missing.get(slot, ())
        }
        if ripe_mask is not None:
            stalled = pend[(direct[pend]) & ripe_mask]
            if len(stalled):
                from fantoch_tpu.errors import StalledExecutionError

                missing_map = {
                    Dot(int(self._slot_src[s]), int(self._slot_seq[s])):
                        row_missing[int(s)]
                    for s in stalled.tolist()[:8]
                }
                raise StalledExecutionError(
                    self._process_id,
                    missing_map,
                    int((now - self._slot_tms[stalled]).max()),
                    self._config.recovery_delay_ms,
                )
        # forward-propagate blockedness (MISSING cells whose dot is NOT
        # lost) to dependents; an old pending row left uncovered means an
        # execution was lost — panic naming the dots (host twin contract)
        blocked = ((deps == MISSING).any(axis=1)) & direct
        valid = deps >= 0
        safe = np.clip(deps, 0, cap - 1)
        old_slots = np.zeros(cap, dtype=bool)
        old_slots[pend[old_mask]] = True
        while True:
            uncovered = old_slots & ~blocked
            if not uncovered.any():
                return nudge
            grown = blocked | np.where(valid, blocked[safe], False).any(axis=1)
            if (grown == blocked).all():
                break
            blocked = grown
        dots = [
            Dot(int(self._slot_src[s]), int(self._slot_seq[s]))
            for s in np.nonzero(uncovered)[0][:8]
        ]
        raise AssertionError(
            f"p{self._process_id}: {int(uncovered.sum())} commands pending "
            f"without missing dependencies: {dots}"
        )

    # --- DevicePlane state hooks ---

    def _fresh_state(self):
        return (
            np.full((self._cap, self._width), TERMINAL, dtype=np.int32),
            np.full(self._cap, -1, dtype=np.int32),
            np.zeros(self._cap, dtype=np.int32),
            np.zeros(self._cap, dtype=np.int32),
            np.zeros(self._cap, dtype=bool),
            np.zeros(self._cap, dtype=bool),
        )

    def _pad_state(self, state, cap: int):
        deps, key, src, seq, occ, executed = state
        rows = min(len(key), cap)
        cols = min(deps.shape[1], self._width)
        out = [
            np.full((cap, self._width), TERMINAL, dtype=np.int32),
            np.full(cap, -1, dtype=np.int32),
            np.zeros(cap, dtype=np.int32),
            np.zeros(cap, dtype=np.int32),
            np.zeros(cap, dtype=bool),
            np.zeros(cap, dtype=bool),
        ]
        out[0][:rows, :cols] = deps[:rows, :cols]
        out[1][:rows] = key[:rows]
        out[2][:rows] = src[:rows]
        out[3][:rows] = seq[:rows]
        out[4][:rows] = occ[:rows]
        out[5][:rows] = executed[:rows]
        return tuple(out)

    # --- durability (in-flight rounds cannot survive a pickle) ---

    def __getstate__(self):
        self.drain_all()
        if self._fault_armed and self._twin_log:
            # fold so the pickled log is empty (entries hold live time
            # handles); post-drain replays dedup to no-op emissions
            self._twin_fold()
        return super().__getstate__()
