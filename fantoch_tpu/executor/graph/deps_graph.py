"""DependencyGraph: orders committed commands by SCC/topological order.

Reference: fantoch_ps/src/executor/graph/mod.rs:46-678.  Commands arrive as
(dot, cmd, deps); each add triggers an SCC search from that dot.  Found SCCs
move to the ``to_execute`` queue (intra-SCC order = dot order) and unblock
pending dependents; missing dependencies park the command in the pending
index (and, under partial replication, produce cross-shard info requests).

This is the *host oracle* implementation.  The batched TPU path
(fantoch_tpu/ops/graph_resolve.py + executor/graph/batched.py) resolves the
same graphs with identical per-key order; the permutation tests assert
equality.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Set, Union

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId, all_process_ids
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.base import ExecutorMetricsKind
from fantoch_tpu.executor.graph.indexes import (
    MONITOR_PENDING_THRESHOLD_MS,
    PendingIndex,
    VertexIndex,
)
from fantoch_tpu.executor.graph.tarjan import FinderResult, TarjanSCCFinder, Vertex
from fantoch_tpu.protocol.common.graph_deps import Dependency


class RequestReplyInfo:
    """RequestReply::Info (mod.rs:33-42)."""

    __slots__ = ("dot", "cmd", "deps")

    def __init__(self, dot: Dot, cmd: Command, deps: List[Dependency]):
        self.dot = dot
        self.cmd = cmd
        self.deps = deps


class RequestReplyExecuted:
    """RequestReply::Executed (mod.rs:39-42)."""

    __slots__ = ("dot",)

    def __init__(self, dot: Dot):
        self.dot = dot


RequestReply = Union[RequestReplyInfo, RequestReplyExecuted]


class DependencyGraph:
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self.executor_index = 0
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        ids = [pid for pid, _ in all_process_ids(config.shard_count, config.n)]
        self._executed_clock: AEClock = AEClock(ids)
        self._vertex_index = VertexIndex(process_id)
        self._pending_index = PendingIndex(process_id, shard_id, config)
        self._finder = TarjanSCCFinder(process_id, shard_id, config)
        self._metrics: Metrics = Metrics()
        # main executor (index 0) outputs:
        self._to_execute: Deque[Command] = deque()
        self._out_requests: Dict[ShardId, Set[Dot]] = {}
        self._added_to_executed_clock: Set[Dot] = set()
        # secondary executor (index > 0) state:
        self._buffered_in_requests: Dict[ShardId, Set[Dot]] = {}
        self._out_request_replies: Dict[ShardId, List[RequestReply]] = {}

    def share_vertex_index(self, primary: "DependencyGraph") -> None:
        """Point this (secondary) graph at the primary's vertex index — the
        reference's SharedMap sharing across executor clones
        (index.rs:19-22).  Request serving must see pending vertices:
        executed-only answers deadlock cross-shard dependency cycles."""
        self._vertex_index = primary._vertex_index

    # --- outputs ---

    def command_to_execute(self) -> Optional[Command]:
        return self._to_execute.popleft() if self._to_execute else None

    def commands_to_execute(self) -> List[Command]:
        out, self._to_execute = list(self._to_execute), deque()
        return out

    def to_executors(self) -> Optional[Set[Dot]]:
        if not self._added_to_executed_clock:
            return None
        out, self._added_to_executed_clock = self._added_to_executed_clock, set()
        return out

    def requests(self) -> Dict[ShardId, Set[Dot]]:
        out, self._out_requests = self._out_requests, {}
        return out

    def request_replies(self) -> Dict[ShardId, List[RequestReply]]:
        out, self._out_request_replies = self._out_request_replies, {}
        return out

    def metrics(self) -> Metrics:
        return self._metrics

    def executed_clock(self) -> AEClock:
        return self._executed_clock

    # --- periodic ---

    def cleanup(self, time: SysTime) -> None:
        if self.executor_index > 0:
            buffered, self._buffered_in_requests = self._buffered_in_requests, {}
            for from_shard, dots in buffered.items():
                self.process_requests(from_shard, dots, time)

    def monitor_pending(self, time: SysTime):
        if self.executor_index == 0:
            fail_ms = self._config.executor_pending_fail_ms
            # a fail bound below the log threshold must still be honored:
            # the scan's early-skip would otherwise silently floor it
            threshold = (
                MONITOR_PENDING_THRESHOLD_MS
                if fail_ms is None
                else min(MONITOR_PENDING_THRESHOLD_MS, fail_ms)
            )
            return self._vertex_index.monitor_pending(
                self._executed_clock,
                threshold,
                time,
                fail_missing_after_ms=fail_ms,
                recovery_delay_ms=self._config.recovery_delay_ms,
            )
        return None

    def handle_executed(self, dots: Set[Dot], _time: SysTime) -> None:
        """Secondary executors absorb executed notifications from the main."""
        if self.executor_index > 0:
            for dot in dots:
                self._executed_clock.add(dot.source, dot.sequence)

    # --- main entry points ---

    def handle_add(self, dot: Dot, cmd: Command, deps: List[Dependency], time: SysTime) -> None:
        assert self.executor_index == 0
        vertex = Vertex(dot, cmd, deps, time)
        if self._vertex_index.index(vertex) is not None:
            raise AssertionError(f"p{self._process_id}: tried to index already indexed {dot}")

        result, abort_missing, _count = self._find_scc(first_find=True, dot=dot)
        dots = self._drain_sccs(time)
        visited, accumulated_missing = self._finder.finalize(self._vertex_index)

        if result is FinderResult.MISSING_DEPENDENCIES:
            self._index_pending(dot, abort_missing)
        elif result is FinderResult.NOT_FOUND:
            assert accumulated_missing, (
                "either there's a missing dependency, or we should find an SCC"
            )
            self._index_pending(dot, accumulated_missing)
        elif result is FinderResult.NOT_PENDING:
            raise AssertionError("just added dot must be pending")

        self._check_pending(dots, time)

    def handle_add_batch(self, adds, time: SysTime) -> None:
        """Bulk add: ``adds`` is an iterable of (dot, cmd, deps).

        The host oracle processes them one by one; the batched subclass
        overrides this to index everything first and resolve once — the
        shape a queue-draining runner (and the bench) feeds.
        """
        for dot, cmd, deps in adds:
            self.handle_add(dot, cmd, deps, time)

    def handle_noop(self, dot: Dot, time: SysTime) -> None:
        """A recovered-noop commit: count the dot as executed and retry its
        dependents — the RequestReplyExecuted path minus the network.  The
        batched subclass inherits this unchanged: its ``_executed_clock``
        aliases the device frontier and its ``_check_pending`` override
        marks the backlog dirty for the next resolve."""
        assert self.executor_index == 0
        self._executed_clock.add(dot.source, dot.sequence)
        self._added_to_executed_clock.add(dot)
        self._check_pending([dot], time)

    def handle_request(self, from_shard: ShardId, dots: Set[Dot], time: SysTime) -> None:
        assert self.executor_index > 0
        self._metrics.aggregate(ExecutorMetricsKind.IN_REQUESTS, 1)
        self.process_requests(from_shard, dots, time)

    def process_requests(self, from_shard: ShardId, dots, time: SysTime) -> None:
        """Answer a peer shard's request for dependency info (mod.rs:300-375)."""
        assert self.executor_index > 0
        for dot in dots:
            vertex = self._vertex_index.find(dot)
            if vertex is not None:
                assert not vertex.cmd.replicated_by(from_shard), (
                    f"{dot} is replicated by requesting shard {from_shard}"
                )
                self._out_request_replies.setdefault(from_shard, []).append(
                    RequestReplyInfo(dot, vertex.cmd, vertex.deps)
                )
            elif self._executed_clock.contains(dot.source, dot.sequence):
                self._out_request_replies.setdefault(from_shard, []).append(
                    RequestReplyExecuted(dot)
                )
            else:
                # not known yet: buffer and retry on cleanup
                self._buffered_in_requests.setdefault(from_shard, set()).add(dot)

    def handle_request_reply(self, infos: List[RequestReply], time: SysTime) -> None:
        assert self.executor_index == 0
        for info in infos:
            if isinstance(info, RequestReplyInfo):
                self.handle_add(info.dot, info.cmd, info.deps, time)
            else:
                self._executed_clock.add(info.dot.source, info.dot.sequence)
                self._added_to_executed_clock.add(info.dot)
                self._check_pending([info.dot], time)

    # --- internals ---

    def _find_scc(self, first_find: bool, dot: Dot):
        vertex = self._vertex_index.find(dot)
        if vertex is None:
            return FinderResult.NOT_PENDING, None, 0
        return self._finder.strong_connect(
            first_find,
            dot,
            vertex,
            self._executed_clock,
            self._added_to_executed_clock,
            self._vertex_index,
        )

    def _drain_sccs(self, time: SysTime) -> List[Dot]:
        """Move found SCCs into the execute queue; returns their dots."""
        dots: List[Dot] = []
        for scc in self._finder.sccs():
            self._metrics.collect(ExecutorMetricsKind.CHAIN_SIZE, len(scc))
            for dot in scc:
                vertex = self._vertex_index.remove(dot)
                assert vertex is not None, "dots from an SCC should exist"
                dots.append(dot)
                self._metrics.collect(
                    ExecutorMetricsKind.EXECUTION_DELAY, vertex.duration_ms(time)
                )
                self._to_execute.append(vertex.cmd)
        return dots

    def _index_pending(self, dot: Dot, missing_deps: Set[Dependency]) -> None:
        requests = 0
        for dep in missing_deps:
            target = self._pending_index.index(dep, dot)
            if target is not None:
                dep_dot, target_shard = target
                requests += 1
                self._out_requests.setdefault(target_shard, set()).add(dep_dot)
        self._metrics.aggregate(ExecutorMetricsKind.OUT_REQUESTS, requests)

    def _check_pending(self, dots: List[Dot], time: SysTime) -> None:
        """Breadth of newly-executed dots -> retry their pending dependents
        (mod.rs:558-644)."""
        assert self.executor_index == 0
        dots = list(dots)
        while dots:
            dot = dots.pop()
            pending = self._pending_index.remove(dot)
            if pending is None:
                continue
            visited: Set[Dot] = set()
            for pending_dot in pending:
                if pending_dot in visited:
                    continue
                result, abort_missing, _cnt = self._find_scc(False, pending_dot)
                new_dots = self._drain_sccs(time)
                new_visited, accumulated_missing = self._finder.finalize(self._vertex_index)
                if result is FinderResult.MISSING_DEPENDENCIES:
                    self._index_pending(pending_dot, abort_missing)
                elif result is FinderResult.NOT_FOUND:
                    self._index_pending(pending_dot, accumulated_missing)
                if result is not FinderResult.NOT_PENDING:
                    if new_dots:
                        visited.clear()
                    else:
                        visited.update(new_visited)
                dots.extend(new_dots)
