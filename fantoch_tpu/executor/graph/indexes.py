"""Vertex and pending indexes for the dependency graph.

Reference: fantoch_ps/src/executor/graph/index.rs.  ``VertexIndex`` maps
committed-but-unexecuted dots to their vertices; ``PendingIndex`` maps a
missing dependency dot to the dots waiting on it.  ``monitor_pending`` is
the liveness watchdog: a command pending past the threshold with no missing
dependencies means the executor lost an execution — panic loudly.
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Set, Tuple

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.graph.tarjan import Vertex
from fantoch_tpu.protocol.common.graph_deps import Dependency
from fantoch_tpu.utils import logger

MONITOR_PENDING_THRESHOLD_MS = 1000


class VertexIndex:
    def __init__(self, process_id: ProcessId):
        self._process_id = process_id
        self._index: Dict[Dot, Vertex] = {}

    def index(self, vertex: Vertex) -> Optional[Vertex]:
        """Index a vertex, returning any previously indexed vertex for the dot."""
        prev = self._index.get(vertex.dot)
        self._index[vertex.dot] = vertex
        return prev

    def dots(self) -> Iterator[Dot]:
        return iter(self._index.keys())

    def find(self, dot: Dot) -> Optional[Vertex]:
        return self._index.get(dot)

    def remove(self, dot: Dot) -> Optional[Vertex]:
        return self._index.pop(dot, None)

    def __len__(self) -> int:
        return len(self._index)

    def monitor_pending(
        self,
        executed_clock: AEClock,
        threshold_ms: int,
        time: SysTime,
        fail_missing_after_ms: Optional[int] = None,
        recovery_delay_ms: Optional[int] = None,
    ) -> Set[Dot]:
        """Log long-pending commands; panic on pending-with-no-missing-deps
        (index.rs:53-103).  With ``fail_missing_after_ms`` set, a command
        whose *missing* dependencies stay uncommitted past that bound
        raises a typed StalledExecutionError — the bounded-wait contract
        for dependencies owned by crashed replicas (a dot whose
        coordinator died before broadcasting commit never commits, and
        without this the executor waits on it forever).

        Returns the union of missing dependency dots seen below the fail
        bound: the runner feeds them to the protocol's recovery plane
        (``Protocol.nudge_recovery``), which can commit a dot the executor
        is starving on even when no live process ever got its payload (the
        noop path)."""
        now = time.millis()
        stuck_without_missing: Set[Dot] = set()
        stalled_missing: dict = {}
        stalled_for = 0
        all_missing: Set[Dot] = set()
        for vertex in self._index.values():
            pending_for = now - vertex.start_time_ms
            if pending_for < threshold_ms:
                continue
            visited: Set[Dot] = set()
            missing = self._missing_dependencies(vertex, executed_clock, visited)
            logger.info(
                "p%s: %s pending for %sms with deps %s | missing %s",
                self._process_id,
                vertex.dot,
                pending_for,
                vertex.deps,
                missing,
            )
            if not missing:
                stuck_without_missing.add(vertex.dot)
            else:
                all_missing |= missing
                if (
                    fail_missing_after_ms is not None
                    and pending_for >= fail_missing_after_ms
                ):
                    stalled_missing[vertex.dot] = missing
                    stalled_for = max(stalled_for, pending_for)
        if stuck_without_missing:
            raise AssertionError(
                f"p{self._process_id}: commands pending without missing "
                f"dependencies: {stuck_without_missing}"
            )
        if stalled_missing:
            from fantoch_tpu.errors import StalledExecutionError

            raise StalledExecutionError(
                self._process_id, stalled_missing, stalled_for, recovery_delay_ms
            )
        return all_missing

    def _missing_dependencies(
        self, vertex: Vertex, executed_clock: AEClock, visited: Set[Dot]
    ) -> Set[Dot]:
        """Transitively collect missing (neither executed nor pending) deps."""
        missing: Set[Dot] = set()
        stack = [vertex]
        while stack:
            v = stack.pop()
            if v.dot in visited:
                continue
            visited.add(v.dot)
            for dep in v.deps:
                dep_dot = dep.dot
                if executed_clock.contains(dep_dot.source, dep_dot.sequence):
                    continue
                dep_vertex = self._index.get(dep_dot)
                if dep_vertex is not None:
                    stack.append(dep_vertex)
                else:
                    missing.add(dep_dot)
        return missing


class PendingIndex:
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        self._index: Dict[Dot, Set[Dot]] = {}

    def index(self, parent: Dependency, dot: Dot) -> Optional[Tuple[Dot, ShardId]]:
        """Record `dot` waiting on `parent`; on first sighting of a parent not
        replicated here, return (dep dot, owner shard) to request its info
        (index.rs:171-205)."""
        children = self._index.get(parent.dot)
        if children is not None:
            children.add(dot)
            return None
        self._index[parent.dot] = {dot}
        assert parent.shards is not None, "shards should be set if it's not a noop"
        if self._shard_id not in parent.shards:
            return parent.dot, parent.dot.target_shard(self._config.n)
        return None

    def remove(self, dep_dot: Dot) -> Optional[Set[Dot]]:
        return self._index.pop(dep_dot, None)

    def __len__(self) -> int:
        return len(self._index)
