"""Tarjan SCC finder over the commit dependency graph — the host-side
execution-ordering oracle.

Reference: fantoch_ps/src/executor/graph/tarjan.rs:99-319.  Differences from
a textbook Tarjan:
- dependencies already executed (per the executed clock) are pruned;
- a missing dependency (not executed, not yet committed here) aborts the
  search (single shard / non-first find) or is accumulated so all missing
  deps can be requested at once (partial replication, first find);
- SCC members are added to the executed clock *eagerly* while popping, so
  later searches in the same batch skip them (tarjan.rs:274-299 — the
  order-sensitive optimization covered by the regression tests);
- SCC members are sorted by dot, which defines intra-SCC execution order.

The reference recurses; Python cannot recurse half-a-million deep chains, so
``strong_connect`` here runs an explicit-stack DFS with identical semantics.
The TPU counterpart of this walk is the batched resolver in
fantoch_tpu/ops/graph_resolve.py, integrated at this seam by
fantoch_tpu/executor/graph/batched.py.
"""

from __future__ import annotations

from enum import Enum
from typing import List, Optional, Set, Tuple

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.protocol.common.graph_deps import Dependency

# commands are sorted inside an SCC by their dot
SCC = List[Dot]


class Vertex:
    __slots__ = ("dot", "cmd", "deps", "start_time_ms", "id", "low", "on_stack")

    def __init__(self, dot: Dot, cmd: Command, deps: List[Dependency], time: SysTime):
        self.dot = dot
        self.cmd = cmd
        self.deps = deps
        self.start_time_ms = time.millis()
        # tarjan bookkeeping
        self.id = 0
        self.low = 0
        self.on_stack = False

    def duration_ms(self, time: SysTime) -> int:
        return time.millis() - self.start_time_ms


class FinderResult(Enum):
    FOUND = "found"
    NOT_FOUND = "not_found"
    NOT_PENDING = "not_pending"
    MISSING_DEPENDENCIES = "missing"


class TarjanSCCFinder:
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        self._id = 0
        self._stack: List[Dot] = []  # tarjan stack (not the DFS stack)
        self._sccs: List[SCC] = []
        self._missing_deps: Set[Dependency] = set()

    def sccs(self) -> List[SCC]:
        sccs, self._sccs = self._sccs, []
        return sccs

    def finalize(self, vertex_index) -> Tuple[Set[Dot], Set[Dependency]]:
        """Reset finder state: clears ids of vertices still on the tarjan
        stack, returning (visited dots, accumulated missing deps)."""
        self._id = 0
        visited: Set[Dot] = set()
        while self._stack:
            dot = self._stack.pop()
            vertex = vertex_index.find(dot)
            assert vertex is not None, "stack member should exist"
            vertex.id = 0
            vertex.on_stack = False
            visited.add(dot)
        missing, self._missing_deps = self._missing_deps, set()
        return visited, missing

    def strong_connect(
        self,
        first_find: bool,
        root_dot: Dot,
        root_vertex: Vertex,
        executed_clock: AEClock,
        added_to_executed_clock: Set[Dot],
        vertex_index,
    ) -> Tuple[FinderResult, Optional[Set[Dependency]], int]:
        """Explicit-stack DFS from `root_dot`.

        Returns (result, missing deps if aborted, missing_deps_count).  The
        count includes misses accumulated in partial-replication first finds
        (where the search continues instead of aborting).
        """
        single_shard_abort = self._config.shard_count == 1 or not first_find

        # DFS frame: [vertex, next dep index, subtree missing count]
        frames: List[List] = []

        def push_frame(vertex: Vertex) -> None:
            self._id += 1
            vertex.id = vertex.low = self._id
            vertex.on_stack = True
            self._stack.append(vertex.dot)
            frames.append([vertex, 0, 0])

        push_frame(root_vertex)
        root_found = False

        while frames:
            frame = frames[-1]
            vertex: Vertex = frame[0]
            advanced = False
            while frame[1] < len(vertex.deps):
                dep = vertex.deps[frame[1]]
                frame[1] += 1
                dep_dot = dep.dot
                # ignore self-dependencies and executed deps
                if dep_dot == vertex.dot or executed_clock.contains(
                    dep_dot.source, dep_dot.sequence
                ):
                    continue
                dep_vertex = vertex_index.find(dep_dot)
                if dep_vertex is None:
                    # missing dependency
                    if single_shard_abort:
                        return FinderResult.MISSING_DEPENDENCIES, {dep}, 0
                    self._missing_deps.add(dep)
                    frame[2] += 1
                    continue
                if dep_vertex.id == 0:
                    push_frame(dep_vertex)
                    advanced = True
                    break
                if dep_vertex.on_stack:
                    vertex.low = min(vertex.low, dep_vertex.id)
            if advanced:
                continue

            # all deps processed: close this frame
            frames.pop()
            missing_count = frame[2]
            if missing_count == 0 and vertex.id == vertex.low:
                # SCC root: pop members off the tarjan stack
                scc: List[Dot] = []
                while True:
                    member_dot = self._stack.pop()
                    member_vertex = vertex_index.find(member_dot)
                    assert member_vertex is not None, "stack member should exist"
                    member_vertex.on_stack = False
                    scc.append(member_dot)
                    # eager executed-clock update: later searches in this batch
                    # see these as executed (tarjan.rs:274-299)
                    executed_clock.add(member_dot.source, member_dot.sequence)
                    if self._config.shard_count > 1:
                        added_to_executed_clock.add(member_dot)
                    if member_dot == vertex.dot:
                        break
                scc.sort()  # intra-SCC order is by dot
                self._sccs.append(scc)
                if vertex.dot == root_dot:
                    root_found = True
            if frames:
                parent = frames[-1]
                parent[0].low = min(parent[0].low, vertex.low)
                parent[2] += missing_count

        # DFS complete without aborting
        root_missing = len(self._missing_deps)
        if root_found:
            return FinderResult.FOUND, None, root_missing
        return FinderResult.NOT_FOUND, None, root_missing
