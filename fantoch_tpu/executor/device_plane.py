"""DevicePlane: the shared base of every device-resident executor plane.

ROADMAP item 5 (the refactor items 1-4 are written on top of): the graph
plane, the votes-table plane (executor/table_plane.py) and the Caesar
predecessors plane (executor/pred_plane.py) all need the same machinery,
and before this base each hand-rolled its own copy:

* **donated resident buffers** — the plane's state lives ON DEVICE across
  batches and every dispatch donates it back in.  Buffers fed to donated
  argnums must be XLA-owned copies (``jnp.array``), never
  ``jnp.asarray``/``device_put`` of host numpy: on CPU those zero-copy
  alias the numpy memory, and donation then hands numpy-owned memory to
  XLA — nondeterministic wrong results + glibc heap corruption under the
  persistent compile cache (the PR 4 ownership rule, regression-tested by
  ``test_resident_buffers_never_alias_host_numpy``).  :meth:`_upload`
  is the ONE place resident buffers are created, so the rule cannot be
  re-broken per plane.
* **lazy host-mirror re-materialization** — pickling (the restart plane's
  ``Executor.snapshot`` seam) fetches the resident state into a host
  mirror; device state never survives a pickle, and the next dispatch
  re-materializes from the mirror with exactly ONE counted upload
  (``resident_uploads`` — the restart acceptance signal).
* **residual re-feed** — work a dispatch could not finish comes back as
  residual columns, buffered host-side and prepended to the next feed
  (the table plane's beyond-gap runs), or stays resident on device until
  a later feed unblocks it (the pred plane's missing-blocked rows); the
  base owns the column-buffer variant.
* **per-dispatch counters** — dispatches / occupancy / residual work /
  kernel wall-ms, surfaced through ``Executor.device_counters()`` into
  the metrics snapshot, the tracer, and the bench rows.
* **kernel-threshold switches** — config > env > built-in default
  resolution for the thresholds that route host-vs-kernel work
  (:func:`resolve_threshold`).

Capacity follows a pow2 schedule (``_grow`` doubles) so XLA compiles
O(log) distinct programs as registries fill, and growth of a live
resident state is one fetch + pad + counted re-upload.

**Accelerator fault tolerance** (the PR 17 plane): the base additionally
owns a health state machine (healthy -> suspect -> failed -> rebuilding)
and a *host twin* — the same jitted kernels run statelessly over
host-authoritative numpy state.  Arming the plane
(``Config.device_dispatch_timeout_ms``, ``Config.plane_shadow_rate`` or
an attached :class:`~fantoch_tpu.sim.device_faults.DeviceFaultInjector`)
makes every dispatch log its exact padded kernel inputs; the twin folds
that log on demand by replaying the log through the SAME kernel on
fresh ``jnp.array`` uploads of host-owned state (donation-safe by the
PR 4 rule; the twin's uploads never touch ``resident_uploads``, which
stays the rebuild acceptance signal).  Because kernel, inputs, and
starting state are bit-identical, the twin's outputs are bit-for-bit
what a healthy device would have produced — so:

* a **hang/timeout** (injected, or a real dispatch overrunning the
  deadline) raises a typed ``DeviceFailedError`` *inside* the plane:
  first occurrence marks the plane suspect and retries once; a second
  failure fails over — the resident buffers are dropped and the batch
  (and every batch after it) is served from the twin, bit-for-bit;
* a **silent bit-flip** of a resident column is caught by the sampled
  shadow-check: compare the fetched resident post-state against the
  twin's folded post-state, raise ``DeviceCorruptionError`` naming the
  first diverging row *before* any host bookkeeping consumes the
  poisoned outputs;
* **rebuild** re-uploads the folded twin state through :meth:`_upload`
  (exactly ONE counted ``resident_uploads``) once the injector's fault
  window has closed (or immediately for a genuine live failure), and
  the plane cuts back to device serving.

Unarmed (all three channels off — the default), none of this costs
anything: no log, no twin, dispatch paths unchanged.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from fantoch_tpu.core.kvs import Key
from fantoch_tpu.errors import DeviceCorruptionError, DeviceFailedError
# one canonical pow2 helper (re-exported: the planes import it from here)
from fantoch_tpu.ops.table_ops import next_pow2

# plane health gauge (numeric so merge_counters can max-fold it: worst
# state wins across an executor pool, like the depth gauges)
HEALTH_HEALTHY = 0
HEALTH_REBUILDING = 1
HEALTH_SUSPECT = 2
HEALTH_FAILED = 3
HEALTH_NAMES = {
    HEALTH_HEALTHY: "healthy",
    HEALTH_REBUILDING: "rebuilding",
    HEALTH_SUSPECT: "suspect",
    HEALTH_FAILED: "failed",
}

# armed planes fold the twin log once it holds this many dispatches, so
# an armed-but-never-checked run pays bounded host memory (folding is
# the same kernels replayed on host-uploaded state)
TWIN_FOLD_LIMIT = 64


def resolve_threshold(
    explicit: Optional[int], env_var: str, default: int
) -> int:
    """The shared threshold-knob resolution: an explicit config value
    beats the environment variable beats the built-in default (the
    ``Config.table_kernel_threshold`` precedence, extracted so every
    plane's switches resolve the same way)."""
    if explicit is not None:
        return int(explicit)
    env = os.environ.get(env_var)
    if env:
        return int(env)
    return default


class DevicePlane:
    """Resident device state + fused dispatch per batch: the base class.

    Subclasses define the state as a tuple of host numpy arrays via three
    hooks and get buffer lifecycle, durability and counters for free:

    * :meth:`_fresh_state` — zero state at the current capacity;
    * :meth:`_pad_state` — existing host state re-padded to a (larger)
      capacity (called by :meth:`_grow` and mirror re-materialization);
    * the resident state itself is ``self._resident`` (a tuple of
      XLA-owned device arrays, or None while unmaterialized) — dispatch
      methods call :meth:`_materialize` first, read/donate the tuple,
      and write the kernel's output state back.

    The optional key registry (``bucket``) maps string keys to stable
    device row ids with pow2 capacity; planes keyed by something else
    (the pred plane's dot->slot map) drive ``_grow`` directly.
    """

    __slots__ = (
        "_key_index",
        "_keys",
        "_cap",
        "_resident",
        "_host_mirror",
        "_residuals",
        "dispatches",
        "grows",
        "resident_uploads",
        "stats",
        # --- accelerator fault tolerance ---
        "health",
        "plane_failovers",
        "plane_rebuilds",
        "degraded_ms",
        "last_failure",
        "_injector",
        "_failure_listener",
        "_fault_pid",
        "_fault_seed",
        "_shadow_rate",
        "_timeout_ms",
        "_fault_armed",
        "_twin_state",
        "_twin_log",
        "_last_failure_dispatch",
    )

    # subclasses name themselves for errors/injector matching
    plane_name = "device"

    def __init__(self, capacity: int, stats: Dict[str, float]):
        self._key_index: Dict[Key, int] = {}
        self._keys: List[Key] = []
        self._cap = next_pow2(max(capacity, 2))
        # tuple of device arrays; None = lazy (created on first dispatch)
        self._resident = None
        # host copy awaiting re-materialization (restart/unpickle path);
        # None while the live state is device-resident
        self._host_mirror: Optional[Tuple[np.ndarray, ...]] = None
        # host-buffered residual columns re-fed with the next batch
        self._residuals: Tuple[np.ndarray, ...] = ()
        self.dispatches = 0
        self.grows = 0
        # host->device materializations: 1 for the lazy initial upload,
        # +1 per restore-from-snapshot re-upload and per live grow (the
        # recovery acceptance signal: restart costs ONE upload, not one
        # per batch)
        self.resident_uploads = 0
        # per-dispatch observability tallies (observability/device.py)
        self.stats: Dict[str, float] = dict(stats)
        # --- accelerator fault tolerance (unarmed by default) ---
        self.health = HEALTH_HEALTHY
        self.plane_failovers = 0
        self.plane_rebuilds = 0
        self.degraded_ms = 0.0
        self.last_failure: Optional[BaseException] = None
        self._injector = None
        self._failure_listener = None
        self._fault_pid: Optional[int] = None
        self._fault_seed = 0
        self._shadow_rate = 0.0
        self._timeout_ms: Optional[float] = None
        self._fault_armed = False
        # host-twin shadow: folded host state + the unfolded dispatch log
        self._twin_state: Optional[Tuple[np.ndarray, ...]] = None
        self._twin_log: List = []
        self._last_failure_dispatch = -(1 << 30)

    # --- state hooks (subclass responsibility) ---

    def _fresh_state(self) -> Tuple[np.ndarray, ...]:
        """Zero host state at the current capacity."""
        raise NotImplementedError

    def _pad_state(
        self, state: Tuple[np.ndarray, ...], cap: int
    ) -> Tuple[np.ndarray, ...]:
        """``state`` re-embedded into fresh arrays at capacity ``cap``
        (>= the state's own capacity)."""
        raise NotImplementedError

    # --- key registry (string keys -> stable device rows; optional) ---

    def bucket(self, key: Key) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = len(self._keys)
            self._key_index[key] = idx
            self._keys.append(key)
            if idx >= self._cap:
                self._grow()
        return idx

    @property
    def key_count(self) -> int:
        return len(self._keys)

    # --- buffer lifecycle ---

    def _upload(self, state: Tuple[np.ndarray, ...]) -> None:
        """THE resident-buffer creation point: copies every array into an
        XLA-owned buffer (``jnp.array`` — the donation-safety rule; see
        the module docstring) and counts the upload."""
        import jax.numpy as jnp

        self._resident = tuple(jnp.array(a) for a in state)
        self.resident_uploads += 1

    def _fetch_state(self) -> Tuple[np.ndarray, ...]:
        """One blocking transfer for the whole resident tuple."""
        import jax

        assert self._resident is not None
        return tuple(np.asarray(a) for a in jax.device_get(self._resident))

    def _materialize(self) -> None:
        """Ensure the state is device-resident: lazy initial creation, or
        the ONE re-upload from the host mirror after restore-from-snapshot
        (the restart plane's lazy re-materialization seam)."""
        if self._resident is not None:
            return
        if self._host_mirror is not None:
            state = self._pad_state(self._host_mirror, self._cap)
            self._host_mirror = None
        else:
            state = self._fresh_state()
        self._upload(state)

    def _grow(self) -> None:
        """Double the capacity; pads the resident state when live (one
        host round-trip — rare, amortized by the pow2 schedule).  Armed
        planes pad and re-upload from the folded TWIN state instead of a
        device fetch: the twin is provably clean, so growth never bakes
        an undetected resident bit-flip into the new buffers."""
        new_cap = self._cap * 2
        if self._fault_armed and self._twin_state is not None:
            self._twin_fold()
            self._twin_state = self._pad_state(self._twin_state, new_cap)
            if self._resident is not None:
                self._upload(self._twin_state)
        elif self._resident is not None:
            state = self._fetch_state()
            self._upload(self._pad_state(state, new_cap))
        self._cap = new_cap
        self.grows += 1

    # --- residual re-feed (column-buffer variant) ---

    def _take_residuals(
        self, columns: Tuple[np.ndarray, ...]
    ) -> Tuple[np.ndarray, ...]:
        """Prepend the buffered residual columns to this batch's columns
        (so gap-filling batches coalesce with the runs they unblock) and
        clear the buffer; ``_put_residuals`` re-buffers the dispatch's
        leftover."""
        if not self._residuals:
            return columns
        merged = tuple(
            np.concatenate([r, c]) for r, c in zip(self._residuals, columns)
        )
        self._residuals = ()
        return merged

    def _put_residuals(self, columns: Tuple[np.ndarray, ...]) -> None:
        self._residuals = columns

    @property
    def residual_count(self) -> int:
        return len(self._residuals[0]) if self._residuals else 0

    # --- per-dispatch counters ---

    def _count_dispatch(self, t0: float, **adds: float) -> None:
        """Tally one dispatch: wall time since ``t0`` into
        ``stats["kernel_ms"]`` plus any per-plane increments."""
        self.dispatches += 1
        self.stats["kernel_ms"] += (time.perf_counter() - t0) * 1000.0
        for name, value in adds.items():
            self.stats[name] += value

    # --- accelerator fault tolerance ---

    def configure_faults(
        self, config, seed: int = 0, process_id: Optional[int] = None
    ) -> None:
        """Arm (or leave unarmed) the fault plane from the config: the
        per-dispatch deadline and the shadow-check rate.  Executors call
        this right after constructing the plane, before any dispatch."""
        self._timeout_ms = getattr(config, "device_dispatch_timeout_ms", None)
        self._shadow_rate = getattr(config, "plane_shadow_rate", 0.0) or 0.0
        self._fault_seed = seed
        if process_id is not None:
            self._fault_pid = process_id
        self._refresh_armed()

    def attach_injector(self, injector) -> None:
        """Attach a DeviceFaultInjector (sim/device_faults.py); arming
        the plane as a side effect so failover has a twin to serve from."""
        self._injector = injector
        self._refresh_armed()

    def attach_failure_listener(self, listener) -> None:
        """``listener(plane, exc)`` fires on every failover — the sim
        runner wires it to the nemesis trace + flight-recorder dump."""
        self._failure_listener = listener

    def _refresh_armed(self) -> None:
        self._fault_armed = (
            self._injector is not None
            or self._shadow_rate > 0.0
            or self._timeout_ms is not None
        )

    @property
    def degraded(self) -> bool:
        """True while serving from the host twin (failed, not yet
        cut back)."""
        return self.health in (HEALTH_FAILED, HEALTH_REBUILDING)

    def health_name(self) -> str:
        return HEALTH_NAMES[self.health]

    # --- host twin (armed only) ---

    def _twin_replay(self, state, entry):
        """Replay ONE logged dispatch on host-owned ``state``: run the
        plane's kernel on fresh ``jnp.array`` uploads of the state plus
        the entry's logged columns, and return ``(new_state, outputs)``
        as host numpy.  Bit-for-bit with the resident dispatch by
        construction (same kernel, same inputs)."""
        raise NotImplementedError

    def _twin_note(self, entry) -> None:
        """Log one dispatch's exact padded kernel inputs for the twin
        (no-op unarmed).  Must be called BEFORE the resident dispatch so
        a failure mid-dispatch can still replay it."""
        if not self._fault_armed:
            return
        if self._twin_state is None:
            self._twin_init()
        self._twin_log.append(entry)
        if len(self._twin_log) > TWIN_FOLD_LIMIT:
            self._twin_fold()

    def _twin_init(self) -> None:
        """First armed dispatch: the twin starts from the same state the
        resident plane did — fresh zeros, the restore mirror, or (when
        armed mid-life) a fetch of the current resident state."""
        if self._host_mirror is not None:
            self._twin_state = self._pad_state(self._host_mirror, self._cap)
        elif self._resident is not None:
            self._twin_state = self._fetch_state()
        else:
            self._twin_state = self._fresh_state()

    def _twin_fold(self):
        """Replay every logged dispatch through the kernel, advancing
        the twin state; returns the LAST dispatch's outputs (None when
        the log was empty).  Truncates the log — later entries already
        contain any residual rows the plane re-fed, so discarding the
        replayed residual outputs reproduces the state sequence
        exactly."""
        outputs = None
        state = self._twin_state
        for entry in self._twin_log:
            state, outputs = self._twin_replay(state, entry)
        self._twin_state = state
        self._twin_log = []
        return outputs

    def _twin_resync(self, state: Tuple[np.ndarray, ...]) -> None:
        """Reset the twin to a host-derived state (compaction and the
        other host-mirror rebuilds produce trusted host state directly;
        the pending log described the pre-rebuild layout)."""
        if not self._fault_armed:
            return
        self._twin_state = tuple(np.array(a) for a in state)
        self._twin_log = []

    # --- detection: injected faults, deadline, shadow-check ---

    def _fault_check_pre(self):
        """Consult the injector before a fused dispatch.  hang/raise
        faults raise the typed error here (a hung dispatch never
        completes — short-circuiting it *is* its deadline, kept
        deterministic instead of sleeping the wall budget); a corrupt
        fault is returned for the caller to apply via
        :meth:`_poison_resident`."""
        inj = self._injector
        if inj is None:
            return None
        fault = inj.on_dispatch(self.plane_name, self.dispatches)
        if fault is None:
            return None
        if fault.kind == "hang":
            raise DeviceFailedError(
                self.plane_name, self._fault_pid, "hang",
                self.dispatches, self._timeout_ms,
            )
        if fault.kind == "raise":
            raise DeviceFailedError(
                self.plane_name, self._fault_pid, "raise", self.dispatches,
                cause=RuntimeError("injected XLA runtime error"),
            )
        return fault

    def _poison_resident(self, fault) -> None:
        """Apply an injected corrupt fault: flip ``fault.bit`` of flat
        element 0 of resident state array 0 on device.  Callers apply it
        AFTER the dispatch's resident update (a post-compute HBM flip),
        so the kernel cannot overwrite the flipped cell in the same
        round and a rate-1.0 shadow check catches it deterministically
        on the faulted dispatch; the host twin never sees the flip,
        which is exactly why the compare names it."""
        import jax.numpy as jnp

        self._materialize()
        a = self._resident[0]
        flat = jnp.ravel(a)
        flat = flat.at[0].set(flat[0] ^ np.asarray(1 << fault.bit, a.dtype))
        self._resident = (flat.reshape(a.shape),) + tuple(self._resident[1:])

    def _check_deadline(self, t0: float) -> None:
        """The per-dispatch deadline, measured across dispatch + its
        blocking drain (an XLA dispatch cannot be interrupted portably;
        detection at the drain is when the hang becomes observable)."""
        if self._timeout_ms is None:
            return
        elapsed_ms = (time.perf_counter() - t0) * 1000.0
        if elapsed_ms > self._timeout_ms:
            raise DeviceFailedError(
                self.plane_name, self._fault_pid, "timeout",
                self.dispatches, self._timeout_ms,
            )

    def _shadow_sampled(self) -> bool:
        """Seeded per-dispatch shadow-check decision — a pure function
        of (seed, plane, dispatch #) so same-seed runs sample the same
        dispatches."""
        rate = self._shadow_rate
        if rate <= 0.0 or self._twin_state is None and not self._twin_log:
            return False
        if rate >= 1.0:
            return True
        import random

        draw = random.Random(
            f"{self._fault_seed}:{self.plane_name}:{self.dispatches}"
        ).random()
        return draw < rate

    def _shadow_compare(
        self, device_state: Tuple[np.ndarray, ...]
    ) -> None:
        """Bit-for-bit compare the fetched resident post-state against
        the twin's folded post-state; raises DeviceCorruptionError
        naming the first diverging row (and its key, when the row is in
        the key registry) — the auditor-style attribution."""
        self._twin_fold()
        twin = self._twin_state
        assert twin is not None
        for index, (dev, host) in enumerate(zip(device_state, twin)):
            if dev.shape == host.shape and np.array_equal(dev, host):
                continue
            if dev.shape != host.shape:
                row = 0
            else:
                diverging = np.nonzero(
                    (dev != host).reshape(dev.shape[0], -1).any(axis=1)
                )[0]
                row = int(diverging[0]) if len(diverging) else 0
            key = self._keys[row] if row < len(self._keys) else None
            raise DeviceCorruptionError(
                self.plane_name, self._fault_pid, self.dispatches,
                index, row, key,
            )

    # --- failover + rebuild ---

    def _device_failure(self, exc: BaseException) -> None:
        """One device failure observed (the batch itself is already
        served from the twin by the caller — never re-dispatched: the
        hung program may have half-applied its donation chain, so a
        re-dispatch could double-apply).  A FIRST hang/timeout is
        ambiguous (scheduler hiccup vs dead device): the plane goes
        *suspect*, drops the untrusted resident buffers, and immediately
        probes — a transient blip re-uploads the twin on the spot and
        never counts a failover; a still-broken device (the injector's
        window is open) escalates to FAILED.  A raise or a corruption
        verdict is definitive and fails over directly."""
        self.last_failure = exc
        self._resident = None
        # back-to-back hangs are not a hiccup: a second hang/timeout
        # within two dispatches of a "recovered" one escalates straight
        # to failover instead of flapping suspect -> healthy forever
        repeat = self.dispatches - self._last_failure_dispatch <= 2
        self._last_failure_dispatch = self.dispatches
        if (
            isinstance(exc, DeviceFailedError)
            and exc.kind in ("hang", "timeout")
            and self.health == HEALTH_HEALTHY
            and not repeat
        ):
            self.health = HEALTH_SUSPECT
            if self._probe_recovery():
                return
        self._enter_failed(exc)

    def _probe_recovery(self) -> bool:
        """The suspect probe: when the device answers again (no injector
        window covers it), re-upload the folded twin state and return to
        healthy — a transient hiccup costs one upload, no failover."""
        inj = self._injector
        if inj is not None and not inj.rebuild_allowed(
            self.plane_name, self.dispatches
        ):
            return False
        self._twin_fold()
        if self._twin_state is None:
            return False
        self._upload(self._pad_state(self._twin_state, self._cap))
        self._host_mirror = None
        self.health = HEALTH_HEALTHY
        return True

    def _enter_failed(self, exc: BaseException) -> None:
        self.health = HEALTH_FAILED
        self.plane_failovers += 1
        self.last_failure = exc
        # the resident buffers are no longer trusted (hung program /
        # poisoned donation chain): drop them; the twin is authoritative
        self._resident = None
        listener = self._failure_listener
        if listener is not None:
            listener(self, exc)

    def _note_degraded(self, t0: float) -> None:
        self.degraded_ms += (time.perf_counter() - t0) * 1000.0

    def _maybe_rebuild(self) -> bool:
        """Cut back to device serving: ONE counted re-upload of the
        folded twin state (the restart plane's acceptance signal,
        reused), vetoed while the injector's fault window still covers
        the device."""
        if self.health != HEALTH_FAILED:
            return False
        inj = self._injector
        if inj is not None and not inj.rebuild_allowed(
            self.plane_name, self.dispatches
        ):
            return False
        self.health = HEALTH_REBUILDING
        self._twin_fold()
        assert self._twin_state is not None
        self._upload(self._pad_state(self._twin_state, self._cap))
        self._host_mirror = None
        self.plane_rebuilds += 1
        self.health = HEALTH_HEALTHY
        return True

    def _recover_health(self) -> None:
        """A suspect probe succeeded: the failure was transient."""
        if self.health == HEALTH_SUSPECT:
            self.health = HEALTH_HEALTHY

    def fault_counters(self) -> Dict[str, float]:
        """The fault-plane slice of ``device_counters()`` (prefixed by
        the owning executor): failover/rebuild tallies, degraded wall,
        and the numeric health gauge (max-folded across pools)."""
        return {
            "failovers": self.plane_failovers,
            "rebuilds": self.plane_rebuilds,
            "degraded_ms": self.degraded_ms,
            "health": self.health,
        }

    # --- durability (Executor.snapshot pickles through here) ---

    def _all_slots(self) -> List[str]:
        slots: List[str] = []
        for klass in type(self).__mro__:
            slots.extend(getattr(klass, "__slots__", ()))
        return slots

    def __getstate__(self):
        # injector + listener are runtime wiring (the runner re-attaches
        # them after restore), never part of the durable image
        state = {
            slot: getattr(self, slot)
            for slot in self._all_slots()
            if slot
            not in (
                "_resident", "_host_mirror", "_injector",
                "_failure_listener", "last_failure",
            )
        }
        mirror = self._host_mirror
        if self._resident is not None:
            mirror = self._fetch_state()
        elif self.degraded and self._twin_state is not None:
            # snapshot taken mid-failover: the twin IS the state —
            # fold it so the restored image needs no log replay
            state["_twin_log"] = []
            outputs = self._twin_fold()
            del outputs
            state["_twin_state"] = self._twin_state
            mirror = self._twin_state
        state["_host_mirror"] = mirror
        return state

    def __setstate__(self, state) -> None:
        # fault-plane defaults first: images written before the fault
        # plane existed (or with it unarmed) stay restorable
        self.health = HEALTH_HEALTHY
        self.plane_failovers = 0
        self.plane_rebuilds = 0
        self.degraded_ms = 0.0
        self.last_failure = None
        self._fault_pid = None
        self._fault_seed = 0
        self._shadow_rate = 0.0
        self._timeout_ms = None
        self._fault_armed = False
        self._twin_state = None
        self._twin_log = []
        self._last_failure_dispatch = -(1 << 30)
        for slot, value in state.items():
            setattr(self, slot, value)
        # device state never survives a pickle: the next dispatch
        # re-materializes from the host mirror (ONE counted upload)
        self._resident = None
        self._injector = None
        self._failure_listener = None
