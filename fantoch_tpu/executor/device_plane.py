"""DevicePlane: the shared base of every device-resident executor plane.

ROADMAP item 5 (the refactor items 1-4 are written on top of): the graph
plane, the votes-table plane (executor/table_plane.py) and the Caesar
predecessors plane (executor/pred_plane.py) all need the same machinery,
and before this base each hand-rolled its own copy:

* **donated resident buffers** — the plane's state lives ON DEVICE across
  batches and every dispatch donates it back in.  Buffers fed to donated
  argnums must be XLA-owned copies (``jnp.array``), never
  ``jnp.asarray``/``device_put`` of host numpy: on CPU those zero-copy
  alias the numpy memory, and donation then hands numpy-owned memory to
  XLA — nondeterministic wrong results + glibc heap corruption under the
  persistent compile cache (the PR 4 ownership rule, regression-tested by
  ``test_resident_buffers_never_alias_host_numpy``).  :meth:`_upload`
  is the ONE place resident buffers are created, so the rule cannot be
  re-broken per plane.
* **lazy host-mirror re-materialization** — pickling (the restart plane's
  ``Executor.snapshot`` seam) fetches the resident state into a host
  mirror; device state never survives a pickle, and the next dispatch
  re-materializes from the mirror with exactly ONE counted upload
  (``resident_uploads`` — the restart acceptance signal).
* **residual re-feed** — work a dispatch could not finish comes back as
  residual columns, buffered host-side and prepended to the next feed
  (the table plane's beyond-gap runs), or stays resident on device until
  a later feed unblocks it (the pred plane's missing-blocked rows); the
  base owns the column-buffer variant.
* **per-dispatch counters** — dispatches / occupancy / residual work /
  kernel wall-ms, surfaced through ``Executor.device_counters()`` into
  the metrics snapshot, the tracer, and the bench rows.
* **kernel-threshold switches** — config > env > built-in default
  resolution for the thresholds that route host-vs-kernel work
  (:func:`resolve_threshold`).

Capacity follows a pow2 schedule (``_grow`` doubles) so XLA compiles
O(log) distinct programs as registries fill, and growth of a live
resident state is one fetch + pad + counted re-upload.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from fantoch_tpu.core.kvs import Key
# one canonical pow2 helper (re-exported: the planes import it from here)
from fantoch_tpu.ops.table_ops import next_pow2


def resolve_threshold(
    explicit: Optional[int], env_var: str, default: int
) -> int:
    """The shared threshold-knob resolution: an explicit config value
    beats the environment variable beats the built-in default (the
    ``Config.table_kernel_threshold`` precedence, extracted so every
    plane's switches resolve the same way)."""
    if explicit is not None:
        return int(explicit)
    env = os.environ.get(env_var)
    if env:
        return int(env)
    return default


class DevicePlane:
    """Resident device state + fused dispatch per batch: the base class.

    Subclasses define the state as a tuple of host numpy arrays via three
    hooks and get buffer lifecycle, durability and counters for free:

    * :meth:`_fresh_state` — zero state at the current capacity;
    * :meth:`_pad_state` — existing host state re-padded to a (larger)
      capacity (called by :meth:`_grow` and mirror re-materialization);
    * the resident state itself is ``self._resident`` (a tuple of
      XLA-owned device arrays, or None while unmaterialized) — dispatch
      methods call :meth:`_materialize` first, read/donate the tuple,
      and write the kernel's output state back.

    The optional key registry (``bucket``) maps string keys to stable
    device row ids with pow2 capacity; planes keyed by something else
    (the pred plane's dot->slot map) drive ``_grow`` directly.
    """

    __slots__ = (
        "_key_index",
        "_keys",
        "_cap",
        "_resident",
        "_host_mirror",
        "_residuals",
        "dispatches",
        "grows",
        "resident_uploads",
        "stats",
    )

    def __init__(self, capacity: int, stats: Dict[str, float]):
        self._key_index: Dict[Key, int] = {}
        self._keys: List[Key] = []
        self._cap = next_pow2(max(capacity, 2))
        # tuple of device arrays; None = lazy (created on first dispatch)
        self._resident = None
        # host copy awaiting re-materialization (restart/unpickle path);
        # None while the live state is device-resident
        self._host_mirror: Optional[Tuple[np.ndarray, ...]] = None
        # host-buffered residual columns re-fed with the next batch
        self._residuals: Tuple[np.ndarray, ...] = ()
        self.dispatches = 0
        self.grows = 0
        # host->device materializations: 1 for the lazy initial upload,
        # +1 per restore-from-snapshot re-upload and per live grow (the
        # recovery acceptance signal: restart costs ONE upload, not one
        # per batch)
        self.resident_uploads = 0
        # per-dispatch observability tallies (observability/device.py)
        self.stats: Dict[str, float] = dict(stats)

    # --- state hooks (subclass responsibility) ---

    def _fresh_state(self) -> Tuple[np.ndarray, ...]:
        """Zero host state at the current capacity."""
        raise NotImplementedError

    def _pad_state(
        self, state: Tuple[np.ndarray, ...], cap: int
    ) -> Tuple[np.ndarray, ...]:
        """``state`` re-embedded into fresh arrays at capacity ``cap``
        (>= the state's own capacity)."""
        raise NotImplementedError

    # --- key registry (string keys -> stable device rows; optional) ---

    def bucket(self, key: Key) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = len(self._keys)
            self._key_index[key] = idx
            self._keys.append(key)
            if idx >= self._cap:
                self._grow()
        return idx

    @property
    def key_count(self) -> int:
        return len(self._keys)

    # --- buffer lifecycle ---

    def _upload(self, state: Tuple[np.ndarray, ...]) -> None:
        """THE resident-buffer creation point: copies every array into an
        XLA-owned buffer (``jnp.array`` — the donation-safety rule; see
        the module docstring) and counts the upload."""
        import jax.numpy as jnp

        self._resident = tuple(jnp.array(a) for a in state)
        self.resident_uploads += 1

    def _fetch_state(self) -> Tuple[np.ndarray, ...]:
        """One blocking transfer for the whole resident tuple."""
        import jax

        assert self._resident is not None
        return tuple(np.asarray(a) for a in jax.device_get(self._resident))

    def _materialize(self) -> None:
        """Ensure the state is device-resident: lazy initial creation, or
        the ONE re-upload from the host mirror after restore-from-snapshot
        (the restart plane's lazy re-materialization seam)."""
        if self._resident is not None:
            return
        if self._host_mirror is not None:
            state = self._pad_state(self._host_mirror, self._cap)
            self._host_mirror = None
        else:
            state = self._fresh_state()
        self._upload(state)

    def _grow(self) -> None:
        """Double the capacity; pads the resident state when live (one
        host round-trip — rare, amortized by the pow2 schedule)."""
        new_cap = self._cap * 2
        if self._resident is not None:
            state = self._fetch_state()
            self._upload(self._pad_state(state, new_cap))
        self._cap = new_cap
        self.grows += 1

    # --- residual re-feed (column-buffer variant) ---

    def _take_residuals(
        self, columns: Tuple[np.ndarray, ...]
    ) -> Tuple[np.ndarray, ...]:
        """Prepend the buffered residual columns to this batch's columns
        (so gap-filling batches coalesce with the runs they unblock) and
        clear the buffer; ``_put_residuals`` re-buffers the dispatch's
        leftover."""
        if not self._residuals:
            return columns
        merged = tuple(
            np.concatenate([r, c]) for r, c in zip(self._residuals, columns)
        )
        self._residuals = ()
        return merged

    def _put_residuals(self, columns: Tuple[np.ndarray, ...]) -> None:
        self._residuals = columns

    @property
    def residual_count(self) -> int:
        return len(self._residuals[0]) if self._residuals else 0

    # --- per-dispatch counters ---

    def _count_dispatch(self, t0: float, **adds: float) -> None:
        """Tally one dispatch: wall time since ``t0`` into
        ``stats["kernel_ms"]`` plus any per-plane increments."""
        self.dispatches += 1
        self.stats["kernel_ms"] += (time.perf_counter() - t0) * 1000.0
        for name, value in adds.items():
            self.stats[name] += value

    # --- durability (Executor.snapshot pickles through here) ---

    def _all_slots(self) -> List[str]:
        slots: List[str] = []
        for klass in type(self).__mro__:
            slots.extend(getattr(klass, "__slots__", ()))
        return slots

    def __getstate__(self):
        state = {
            slot: getattr(self, slot)
            for slot in self._all_slots()
            if slot not in ("_resident", "_host_mirror")
        }
        mirror = self._host_mirror
        if self._resident is not None:
            mirror = self._fetch_state()
        state["_host_mirror"] = mirror
        return state

    def __setstate__(self, state) -> None:
        for slot, value in state.items():
            setattr(self, slot, value)
        # device state never survives a pickle: the next dispatch
        # re-materializes from the host mirror (ONE counted upload)
        self._resident = None
