"""SlotExecutor: total-order execution by consecutive slot numbers.

Reference: fantoch_ps/src/executor/slot.rs.  Commands arrive tagged with
their consensus slot; execution simply buffers out-of-order slots and
drains while ``next_slot`` is present.  Sequential (not key-parallel): the
total order is global, not per-key.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ProcessId, ShardId
from fantoch_tpu.core.kvs import KVStore
from fantoch_tpu.executor.base import Executor, ExecutorResult


@dataclass
class SlotExecutionInfo:
    slot: int
    cmd: Command


class SlotExecutor(Executor):
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        self._process_id = process_id
        self._shard_id = shard_id
        self._execute_at_commit = config.execute_at_commit
        # only leader failover legitimately re-chooses a slot (takeover
        # carry-forward); without it a duplicate delivery is a protocol
        # bug the original asserts must keep catching loudly
        self._failover = config.fpaxos_leader_timeout_ms is not None
        self._store = KVStore(
            config.executor_monitor_execution_order,
            config.execution_digests,
        )
        self._next_slot = 1
        self._to_execute: Dict[int, Command] = {}
        self._to_clients: Deque[ExecutorResult] = deque()

    def handle(self, info: SlotExecutionInfo, time) -> None:
        if self._execute_at_commit:
            self._execute(info.cmd)
            return
        if not self._failover:
            assert info.slot >= self._next_slot, "slots execute exactly once"
            assert info.slot not in self._to_execute
        elif info.slot in self._to_execute:
            # re-chosen via takeover carry-forward: exactly once — and the
            # re-chosen value must be the same command (ballots guarantee
            # it; a mismatch is a consensus safety violation)
            assert self._to_execute[info.slot].rifl == info.cmd.rifl, (
                f"slot {info.slot} re-chosen with a different command: "
                f"{self._to_execute[info.slot].rifl} vs {info.cmd.rifl}"
            )
            return
        elif info.slot < self._next_slot:
            return  # already executed (same-value re-choice)
        self._to_execute[info.slot] = info.cmd
        while True:
            cmd = self._to_execute.pop(self._next_slot, None)
            if cmd is None:
                return
            self._execute(cmd)
            self._next_slot += 1

    def _execute(self, cmd: Command) -> None:
        tracer = self.tracer
        if tracer.enabled:
            # slot order reached this command: ready and executed in the
            # same drain (total-order executors have no separate wait)
            tracer.span("ready", cmd.rifl, pid=self._process_id)
        self._to_clients.extend(cmd.execute(self._shard_id, self._store))
        if tracer.enabled:
            tracer.span("executed", cmd.rifl, pid=self._process_id)

    def to_clients(self) -> Optional[ExecutorResult]:
        return self._to_clients.popleft() if self._to_clients else None

    @classmethod
    def parallel(cls) -> bool:
        return False

    def monitor(self):
        return self._store.monitor
