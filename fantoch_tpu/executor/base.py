"""Executor interface: the pluggable ordering engine.

Reference: fantoch/src/executor/mod.rs:27-183.  A protocol emits
``ExecutionInfo`` values; an executor consumes them, decides when commands
are safe to execute (total order, dependency order, timestamp stability...),
runs them on the local KVStore and streams per-key ``ExecutorResult``s back
to clients.  ``MessageKey`` routing hashes keys to executor indices so
key-parallel executors scale across workers.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import Generic, Iterator, NamedTuple, Optional, Tuple, TypeVar

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ProcessId, Rifl, ShardId
from fantoch_tpu.core.kvs import KVOpResult, Key
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.observability.tracer import NOOP_TRACER


class ExecutorResult(NamedTuple):
    """Result of executing one key's ops of a command
    (fantoch/src/executor/mod.rs:169-183).

    A NamedTuple, not a dataclass: results are constructed once per
    executed key on the serving hot path, and tuple construction is
    several times cheaper than a frozen dataclass's __init__."""

    rifl: Rifl
    key: Key
    op_results: Tuple[KVOpResult, ...]


class ExecutorMetricsKind(Enum):
    """Reference: fantoch/src/executor/mod.rs:123-145."""

    EXECUTION_DELAY = "execution_delay"
    CHAIN_SIZE = "chain_size"
    OUT_REQUESTS = "out_requests"
    IN_REQUESTS = "in_requests"
    IN_REQUEST_REPLIES = "in_request_replies"


# ExecutionInfo type produced by the protocol for this executor
Info = TypeVar("Info")


class Executor(ABC, Generic[Info]):
    """Ordering engine interface (fantoch/src/executor/mod.rs:27-121).

    Implementations: BasicExecutor (immediate), GraphExecutor (SCC/topo order
    over the commit dependency graph — the TPU-accelerated one),
    TableExecutor (timestamp stability), PredecessorsExecutor (Caesar
    two-phase), SlotExecutor (total order by slot).
    """

    # lifecycle tracer (observability plane): class-level no-op default so
    # every executor is traceable without touching its __init__; runners
    # install a real tracer per instance via set_tracer
    tracer = NOOP_TRACER

    @abstractmethod
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config): ...

    def set_executor_index(self, index: int) -> None:
        """Executors are cloned per worker; each clone learns its index."""

    def set_tracer(self, tracer) -> None:
        """Runner hook: install the lifecycle tracer
        (fantoch_tpu/observability)."""
        self.tracer = tracer

    def device_counters(self) -> Optional[dict]:
        """Per-dispatch device-plane counters (dispatch count, batch
        occupancy, kernel wall-ms...), folded into the run layer's
        periodic metrics snapshot.  None when this executor drives no
        device plane."""
        return None

    def device_planes(self) -> tuple:
        """The device-resident planes this executor drives (empty when
        none) — the seam the runners use to arm the device-fault nemesis
        (sim/device_faults.py) and attach failure listeners."""
        return ()

    def snapshot(self) -> bytes:
        """Durable image of the executor state (ordering structures,
        KVStore, emit frontier).  Device-resident planes pickle their
        host mirrors and lazily re-materialize on the first dispatch
        after :meth:`restore` (one re-upload, counted by the plane).  The
        tracer is excluded and reattached by the restorer."""
        import pickle

        saved = self.__dict__.pop("tracer", None)
        try:
            return pickle.dumps(self)
        finally:
            if saved is not None:
                self.__dict__["tracer"] = saved

    @classmethod
    def restore(cls, blob: bytes) -> "Executor":
        """Rebuild an executor instance from :meth:`snapshot` output."""
        import pickle

        executor = pickle.loads(blob)
        assert isinstance(executor, Executor), type(executor).__name__
        return executor

    def cleanup(self, time: SysTime) -> None:
        """Periodic housekeeping (cross-shard request retries...)."""

    def monitor_pending(self, time: SysTime):
        """Liveness watchdog: check for stuck-but-satisfiable commands.
        May return a set of missing dependency dots for the runner to feed
        into the protocol's recovery plane (Protocol.nudge_recovery)."""
        return None

    @abstractmethod
    def handle(self, info: Info, time: SysTime) -> None:
        """Consume one ExecutionInfo from the protocol."""

    def handle_batch(self, infos, time: SysTime) -> None:
        """Consume a drained queue of ExecutionInfos at once.

        Drivers call this when several infos are available together (one
        protocol step's output, a queue drain); batch-oriented executors
        (GraphExecutor with the device resolver) override it to amortize
        one device round-trip over the whole batch."""
        for info in infos:
            self.handle(info, time)

    @abstractmethod
    def to_clients(self) -> Optional[ExecutorResult]:
        """Pop one ready result (None when drained)."""

    def to_clients_iter(self) -> Iterator[ExecutorResult]:
        while True:
            result = self.to_clients()
            if result is None:
                return
            yield result

    def to_executors(self) -> Optional[Tuple[ShardId, Info]]:
        """Pop one cross-shard executor message (partial replication only)."""
        return None

    def to_executors_iter(self) -> Iterator[Tuple[ShardId, Info]]:
        while True:
            msg = self.to_executors()
            if msg is None:
                return
            yield msg

    def executed(self, time: SysTime):
        """Committed-and-executed clock for GC (None if unsupported)."""
        return None

    @classmethod
    def parallel(cls) -> bool:
        """Whether this executor can run as multiple key-routed instances."""
        return False

    def metrics(self):
        return getattr(self, "_metrics", None)

    def monitor(self):
        """Execution-order monitor (tests only)."""
        return None

    def digest(self):
        """Per-key chained execution digest (core/audit.ExecutionDigest)
        when ``Config.execution_digests`` is on; None otherwise.  Every
        concrete executor funnels execution through a KVStore, so the
        shared lookup here covers them all."""
        store = getattr(self, "_store", None)
        return store.digest if store is not None else None


class MessageKey:
    """Key-based worker routing for execution infos
    (fantoch/src/executor/mod.rs:147-166): route to
    ``hash(key) % executors``."""

    @staticmethod
    def key_index(key: Key, executors: int) -> int:
        from fantoch_tpu.utils import key_hash

        return key_hash(key) % executors
