"""Host-environment helpers that must run *before* jax backend init.

On this machine a sitecustomize hook registers the TPU plugin at
interpreter start, with two consequences (round-1 postmortem, reproduced):

  * ``JAX_PLATFORMS=cpu`` set in the *parent environment* hangs interpreter
    start, so CPU forcing cannot be done via env vars across a process
    boundary;
  * backend init on the TPU plugin can block indefinitely and
    uninterruptibly, so the only safe point to force a platform is
    in-Python, before the first backend touch.

:func:`force_cpu_platform` is that single shared workaround — used by
tests/conftest.py, __graft_entry__.dryrun_multichip and bench.py.  Keeping
it in one place means a jax upgrade or hook change is fixed once.
"""

from __future__ import annotations

import os
import re


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Force the CPU platform (optionally with n virtual devices).

    Must be called before jax initializes a backend; a no-op guard is the
    caller's job (see __graft_entry__.dryrun_multichip for the pattern of
    checking ``jax._src.xla_bridge._backends`` and re-execing when too
    late).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compile cache — an optimization only; failures are
    swallowed (the experimental jax.config flag names may change).

    One shared helper for bench.py, tests/conftest.py and the dryrun:
    first-ever compiles (remote-compile tunnel: minutes; the 8-device
    virtual mesh: ~1 min/test-module) are cached in-repo and reload
    sub-second.  Entries are keyed by program+topology+compiler version,
    so a stale cache can only miss, never corrupt."""
    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache",
        )
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception as exc:  # noqa: BLE001 — cache is an optimization only
        # loud enough to diagnose "why did CI get slow" if a jax upgrade
        # renames the flags; harmless otherwise
        import sys

        print(f"# compile cache unavailable: {exc!r}", file=sys.stderr)
