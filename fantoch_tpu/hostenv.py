"""Host-environment helpers that must run *before* jax backend init.

On this machine a sitecustomize hook registers the TPU plugin at
interpreter start, with two consequences (round-1 postmortem, reproduced):

  * ``JAX_PLATFORMS=cpu`` set in the *parent environment* hangs interpreter
    start, so CPU forcing cannot be done via env vars across a process
    boundary;
  * backend init on the TPU plugin can block indefinitely and
    uninterruptibly, so the only safe point to force a platform is
    in-Python, before the first backend touch.

:func:`force_cpu_platform` is that single shared workaround — used by
tests/conftest.py, __graft_entry__.dryrun_multichip and bench.py.  Keeping
it in one place means a jax upgrade or hook change is fixed once.
"""

from __future__ import annotations

import os
import re


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Force the CPU platform (optionally with n virtual devices).

    Must be called before jax initializes a backend; a no-op guard is the
    caller's job (see __graft_entry__.dryrun_multichip for the pattern of
    checking ``jax._src.xla_bridge._backends`` and re-execing when too
    late).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
