"""Pallas-fused resolve kernels for the hot device-plane dispatches.

The BASELINE north star names a *Pallas kernel* for conflict detection +
order resolution; until this module every resolve path was XLA-composed
(`lax.scan` chains, peel-and-compact, scatter pipelines).  XLA fuses
elementwise work but materializes every scatter/gather boundary to HBM —
on a plane dispatch that is the install scatter, the waiter-index patch,
*and every iteration* of the dependency fixpoint.  A Pallas kernel
compiles the whole dispatch body as ONE Mosaic program whose
intermediates (the ``int32[C, W]`` dep-slot matrix, the dot's
clock/src columns, the fixpoint's executable mask) stay VMEM-resident
from the install through the last fixpoint sweep — the "explicit VMEM
blocking" the ROADMAP item asks for is exactly this residency, guarded
by :func:`_fits_vmem` so an oversized window routes back to the
composed program instead of faulting the chip.

Three kernel families, matching the three plane dispatches:

* :func:`pred_plane_step_pallas` — Caesar's resident window step
  (install new rows + dep-cell patches + the two-phase committed/
  lower-clock fixpoint) as one hand-written kernel body.
* :func:`graph_plane_step_pallas` — the EPaxos/Atlas backlog step
  (install + waiter-index patch + executed fold + mode-routed resolve).
  The resolve core is shared *by construction* with the composed path
  (``ops.graph_resolve.graph_plane_step_core``): the kernel body traces
  the identical program, so resolved/stuck/rank/order parity is exact,
  and on TPU the whole step lowers as one fused program where Mosaic
  supports the traced ops (the sort-based keyed core may refuse to
  lower — the router's first-dispatch probe then falls back to the
  composed program for the life of the process).
* :func:`votes_commit_pallas` / :func:`table_round_pallas` — the fused
  table round (vote-range coalesce + frontier advance + stability order
  statistic as one kernel), sharing ``ops.table_ops`` cores the same
  way.

**Contract** (enforced by tests/test_pallas_resolve.py): bit-for-bit
equality with the composed kernels — same resolved/stuck/rank/order,
same residual-column protocol — and unchanged donation discipline: the
resident state aliases in-place through ``input_output_aliases`` under
the same ``donate_argnums`` the composed programs use, so
``resident_uploads == 1`` holds whichever route serves.

**Routing** (``Config.pallas_kernels`` > ``FANTOCH_PALLAS`` env > the
backend default): the public ops symbols (``resolve_pred_plane_step``,
``resolve_graph_plane_step``, ``fused_votes_commit``,
``fused_table_round``) are routers that consult :func:`pallas_enabled`
per dispatch.  The default is ON for TPU backends (where the fusion
pays) and OFF elsewhere: on the CPU dev pin the kernels execute in
Pallas *interpret mode* — the kernel body discharges to the same XLA
ops, so parity is testable on every push (the parity suite and
``make pallas-smoke`` force the route on), but interpret dispatch adds
pure overhead to a serving loop, so CPU serving keeps the composed
programs unless ``FANTOCH_PALLAS=1`` opts in.  ``FANTOCH_PALLAS=0`` is
the escape hatch that forces the composed path everywhere, including
TPU.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from fantoch_tpu.ops.graph_resolve import (
    GraphPlaneStep,
    TERMINAL,
    graph_plane_step_core,
)
from fantoch_tpu.ops.pred_resolve import PredPlaneStep
from fantoch_tpu.ops.table_ops import _fused_round_core, _votes_commit_core

logger = logging.getLogger(__name__)

# conservative per-dispatch VMEM budget for the fused kernels: the whole
# resident window plus the feed columns must fit on-core or the dispatch
# routes to the composed program (which tiles through HBM instead of
# faulting).  v4 cores have 16 MiB of VMEM per core; half is headroom
# for Mosaic's own temporaries.
_VMEM_BUDGET_BYTES = 8 * (1 << 20)

# ---------------------------------------------------------------------------
# routing: Config.pallas_kernels > FANTOCH_PALLAS env > backend default
# ---------------------------------------------------------------------------

_override: Optional[bool] = None
# first-dispatch probe verdict per kernel family: None = untried,
# True = compiled+ran, False = refused to lower (composed fallback for
# the life of the process — lowering failures are deterministic)
_supported: Dict[str, Optional[bool]] = {}


def set_pallas_kernels(enabled: Optional[bool]) -> None:
    """Process-global route override: ``True``/``False`` pin the route,
    ``None`` returns to env/backend resolution.  Like the recompile
    counters this is process-global — co-hosted executors with
    conflicting configs share one route (last writer wins)."""
    global _override
    _override = enabled


def apply_pallas_config(config) -> None:
    """Executor-construction seam: fold ``Config.pallas_kernels`` into
    the route (an explicit config value beats the env var; ``None``
    leaves env/backend resolution in place — the
    ``Config.device_graph_plane`` precedence convention)."""
    value = getattr(config, "pallas_kernels", None)
    if value is not None:
        set_pallas_kernels(bool(value))


def pallas_enabled() -> bool:
    """Resolve the route for the next dispatch: explicit override
    (config) > ``FANTOCH_PALLAS`` env > default (on for TPU backends,
    off elsewhere — interpret mode is a parity instrument, not a CPU
    win; see the module docstring)."""
    if _override is not None:
        return _override
    env = os.environ.get("FANTOCH_PALLAS")
    if env is not None and env != "":
        return env not in ("0", "false", "False", "off")
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # backend init failure: the composed path works
        return False


def _interpret() -> bool:
    """Interpret-mode switch: anything that is not a real TPU backend
    runs the kernel body through the Pallas interpreter (bit-for-bit
    the same ops, no Mosaic lowering)."""
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _fits_vmem(*arrays) -> bool:
    """Whole-state VMEM residency gate (compiled mode only): the fused
    kernel keeps every operand on-core, so the operand total must fit
    the budget.  Interpret mode has no VMEM and always fits."""
    if _interpret():
        return True
    total = 0
    for a in arrays:
        size = 1
        for dim in getattr(a, "shape", ()):
            size *= int(dim)
        total += size * jnp.dtype(getattr(a, "dtype", jnp.int32)).itemsize
    return total <= _VMEM_BUDGET_BYTES


def pallas_status() -> Dict[str, object]:
    """Routing introspection for bench rows and the smoke: the resolved
    route plus each family's probe verdict."""
    return {
        "enabled": pallas_enabled(),
        "interpret": _interpret(),
        "families": dict(_supported),
    }


def route_dispatch(family: str, pallas_fn, composed_fn, args, kwargs):
    """The per-dispatch router: composed path when the route is off or
    the family's probe failed; otherwise the Pallas kernel, with the
    FIRST dispatch per family probing lowering support.  A probe
    failure (Mosaic refusing an op on a real TPU) is caught at compile
    time — before any donated buffer is consumed — so retrying the
    composed program on the same arguments is safe; the family then
    stays on the composed path for the life of the process (lowering
    failures are deterministic, no point re-probing)."""
    if not pallas_enabled():
        return composed_fn(*args, **kwargs)
    verdict = _supported.get(family)
    if verdict is False:
        return composed_fn(*args, **kwargs)
    if verdict:
        return pallas_fn(*args, **kwargs)
    try:
        out = pallas_fn(*args, **kwargs)
    except Exception as exc:  # noqa: BLE001 — unsupported backend/op
        _supported[family] = False
        logger.warning(
            "pallas kernel family %r unsupported on backend %r (%s); "
            "falling back to the composed XLA program for this process",
            family, jax.default_backend(), exc,
        )
        return composed_fn(*args, **kwargs)
    _supported[family] = True
    return out


# ---------------------------------------------------------------------------
# pred plane: install + patch + two-phase fixpoint, hand-written
# ---------------------------------------------------------------------------


def _pred_step_kernel(
    deps_ref, clock_ref, src_ref, occ_ref, exec_ref,
    u_row_ref, u_deps_ref, u_clock_ref, u_src_ref,
    p_row_ref, p_col_ref, p_val_ref,
    o_deps_ref, o_clock_ref, o_src_ref, o_occ_ref, o_exec_ref, o_newly_ref,
):
    """The fused pred-plane dispatch body.  All refs are whole-window
    VMEM blocks; the five state refs alias their outputs in place
    (``input_output_aliases``), so the window never leaves the core
    between the install scatter and the last fixpoint sweep.

    The math is the composed ``resolve_pred_plane_step`` body verbatim
    (ops/pred_resolve.py): (1) full-row install, (2) dep-cell patches,
    (3) the monotone two-phase fixpoint — ``executable(v) = occ(v) and
    every dep slot TERMINAL / executed / committed-with-higher-(clock,
    src)``, iterated to no-change.  Identical deterministic recurrence
    => bit-for-bit identical outputs (the parity contract)."""
    deps = deps_ref[...]
    clock = clock_ref[...]
    src = src_ref[...]
    occ = occ_ref[...]
    executed0 = exec_ref[...]
    u_row = u_row_ref[...]

    # (1) install new rows (pad rows carry row == C and drop)
    deps = deps.at[u_row].set(u_deps_ref[...], mode="drop")
    clock = clock.at[u_row].set(u_clock_ref[...], mode="drop")
    src = src.at[u_row].set(u_src_ref[...], mode="drop")
    occ = occ.at[u_row].set(True, mode="drop")
    executed0 = executed0.at[u_row].set(False, mode="drop")
    # (2) dep patches (missing dots that just committed / noop TERMINAL)
    deps = deps.at[p_row_ref[...], p_col_ref[...]].set(
        p_val_ref[...], mode="drop"
    )

    # (3) two-phase fixpoint over the whole resident window
    in_res = deps >= 0
    safe = jnp.maximum(deps, 0)
    dep_clock, dep_src = clock[safe], src[safe]
    dep_higher = (dep_clock > clock[:, None]) | (
        (dep_clock == clock[:, None]) & (dep_src > src[:, None])
    )
    never_blocks = (deps == TERMINAL) | (in_res & occ[safe] & dep_higher)

    def body(state):
        done, _changed = state
        dep_ok = never_blocks | (in_res & done[safe])
        new = occ & dep_ok.all(axis=1)
        changed = (new & ~done).any()
        return new | done, changed

    first, changed0 = body((executed0, jnp.bool_(True)))
    done, _ = jax.lax.while_loop(lambda s: s[1], body, (first, changed0))

    o_deps_ref[...] = deps
    o_clock_ref[...] = clock
    o_src_ref[...] = src
    o_occ_ref[...] = occ
    o_exec_ref[...] = done
    o_newly_ref[...] = done & ~executed0


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def pred_plane_step_pallas(
    deps, clock, src, occ, executed,
    u_row, u_deps, u_clock, u_src, p_row, p_col, p_val,
) -> PredPlaneStep:
    """Pallas twin of ``resolve_pred_plane_step``: same signature, same
    donation set, same :class:`PredPlaneStep` out — the resident tuple
    aliases in place via ``input_output_aliases`` so donation semantics
    match the composed jit exactly."""
    from jax.experimental import pallas as pl

    cap, width = deps.shape
    out = pl.pallas_call(
        _pred_step_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((cap, width), deps.dtype),
            jax.ShapeDtypeStruct((cap,), clock.dtype),
            jax.ShapeDtypeStruct((cap,), src.dtype),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
        ],
        input_output_aliases={0: 0, 1: 1, 2: 2, 3: 3, 4: 4},
        interpret=_interpret(),
    )(deps, clock, src, occ, executed,
      u_row, u_deps, u_clock, u_src, p_row, p_col, p_val)
    return PredPlaneStep(*out)


# ---------------------------------------------------------------------------
# graph plane: install + patch + executed fold + mode-routed resolve
# ---------------------------------------------------------------------------


def _graph_step_kernel(
    deps_ref, key_ref, src_ref, seq_ref, occ_ref, exec_ref,
    u_row_ref, u_deps_ref, u_key_ref, u_src_ref, u_seq_ref,
    p_row_ref, p_col_ref, p_val_ref, e_row_ref,
    o_deps_ref, o_key_ref, o_src_ref, o_seq_ref, o_occ_ref, o_exec_ref,
    o_order_ref, o_newly_ref, o_stuck_ref, o_leader_ref,
    *, mode: str,
):
    """The fused graph-plane dispatch body: loads the whole backlog into
    VMEM values and traces ``graph_plane_step_core`` — the exact
    composed program — over them, so parity is by construction and the
    prologue scatters, the keyed compression, and the resolve fixpoint
    share one on-core program (no HBM round-trip at the scatter
    boundaries XLA would materialize)."""
    out = graph_plane_step_core(
        deps_ref[...], key_ref[...], src_ref[...], seq_ref[...],
        occ_ref[...], exec_ref[...],
        u_row_ref[...], u_deps_ref[...], u_key_ref[...], u_src_ref[...],
        u_seq_ref[...],
        p_row_ref[...], p_col_ref[...], p_val_ref[...], e_row_ref[...],
        mode=mode,
    )
    o_deps_ref[...] = out.deps
    o_key_ref[...] = out.key
    o_src_ref[...] = out.src
    o_seq_ref[...] = out.seq
    o_occ_ref[...] = out.occ
    o_exec_ref[...] = out.executed
    o_order_ref[...] = out.order
    o_newly_ref[...] = out.newly
    o_stuck_ref[...] = out.stuck
    o_leader_ref[...] = out.leader


@functools.partial(
    jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5), static_argnames=("mode",)
)
def graph_plane_step_pallas(
    deps, key, src, seq, occ, executed,
    u_row, u_deps, u_key, u_src, u_seq,
    p_row, p_col, p_val, e_row,
    *, mode: str,
) -> GraphPlaneStep:
    """Pallas twin of ``resolve_graph_plane_step``: same signature,
    donation set and :class:`GraphPlaneStep` out, resident columns
    aliased in place."""
    from jax.experimental import pallas as pl

    cap, width = deps.shape
    i32 = deps.dtype
    out = pl.pallas_call(
        functools.partial(_graph_step_kernel, mode=mode),
        out_shape=[
            jax.ShapeDtypeStruct((cap, width), i32),
            jax.ShapeDtypeStruct((cap,), i32),
            jax.ShapeDtypeStruct((cap,), i32),
            jax.ShapeDtypeStruct((cap,), i32),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), i32),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), jnp.bool_),
            jax.ShapeDtypeStruct((cap,), i32),
        ],
        input_output_aliases={0: 0, 1: 1, 2: 2, 3: 3, 4: 4, 5: 5},
        interpret=_interpret(),
    )(deps, key, src, seq, occ, executed,
      u_row, u_deps, u_key, u_src, u_seq, p_row, p_col, p_val, e_row)
    return GraphPlaneStep(*out)


# ---------------------------------------------------------------------------
# table plane: vote-range coalesce + frontier + stability as one kernel
# ---------------------------------------------------------------------------


def _votes_commit_kernel(
    frontier_ref, vkey_ref, vby_ref, vstart_ref, vend_ref, valid_ref,
    o_frontier_ref, o_stable_ref, o_rkey_ref, o_rby_ref, o_rstart_ref,
    o_rend_ref, o_residual_ref,
    *, threshold: int,
):
    """The fused table commit body: interval coalesce per (key, process)
    + frontier scatter-max + the stability order statistic, traced from
    the shared ``_votes_commit_core`` over VMEM-resident values —
    including the residual classification (beyond-gap runs return to the
    caller exactly as the composed kernel returns them)."""
    out = _votes_commit_core(
        frontier_ref[...], vkey_ref[...], vby_ref[...], vstart_ref[...],
        vend_ref[...], valid_ref[...], threshold=threshold,
    )
    (o_frontier_ref[...], o_stable_ref[...], o_rkey_ref[...],
     o_rby_ref[...], o_rstart_ref[...], o_rend_ref[...],
     o_residual_ref[...]) = out


@functools.partial(jax.jit, static_argnames=("threshold",), donate_argnums=(0,))
def votes_commit_pallas(frontier, vkey, vby, vstart, vend, valid, *, threshold):
    """Pallas twin of ``fused_votes_commit``: same signature, same
    donated frontier (aliased in place), same 7-tuple out including the
    residual columns."""
    from jax.experimental import pallas as pl

    K, n = frontier.shape
    V = vkey.shape[0]
    i32 = frontier.dtype
    return tuple(
        pl.pallas_call(
            functools.partial(_votes_commit_kernel, threshold=threshold),
            out_shape=[
                jax.ShapeDtypeStruct((K, n), i32),
                jax.ShapeDtypeStruct((K,), i32),
                jax.ShapeDtypeStruct((V,), i32),
                jax.ShapeDtypeStruct((V,), i32),
                jax.ShapeDtypeStruct((V,), i32),
                jax.ShapeDtypeStruct((V,), i32),
                jax.ShapeDtypeStruct((V,), jnp.bool_),
            ],
            input_output_aliases={0: 0},
            interpret=_interpret(),
        )(frontier, vkey, vby, vstart, vend, valid)
    )


def _table_round_kernel(
    prior_ref, frontier_ref, key_ref, min_clock_ref,
    o_prior_ref, o_frontier_ref, o_clock_ref, o_vstart_ref, o_exec_ref,
    o_gaps_ref,
    *, threshold: int, voters: int,
):
    """The fused dense table round (proposal + contiguous votes +
    stability), traced from ``_fused_round_core`` over VMEM values."""
    out = _fused_round_core(
        prior_ref[...], frontier_ref[...], key_ref[...], min_clock_ref[...],
        threshold, voters,
    )
    (o_prior_ref[...], o_frontier_ref[...], o_clock_ref[...],
     o_vstart_ref[...], o_exec_ref[...]) = out[:5]
    o_gaps_ref[...] = out[5][None]


@functools.partial(
    jax.jit, static_argnames=("threshold", "voters"), donate_argnums=(0, 1)
)
def table_round_pallas(prior, frontier, key, min_clock, *, threshold, voters):
    """Pallas twin of ``fused_table_round`` (same signature/donation;
    the scalar ``gaps`` comes back shaped ``[1]`` inside the kernel and
    is squeezed here so the 6-tuple matches the composed out)."""
    from jax.experimental import pallas as pl

    K = prior.shape[0]
    n = frontier.shape[1]
    B = key.shape[0]
    i32 = prior.dtype
    out = pl.pallas_call(
        functools.partial(
            _table_round_kernel, threshold=threshold, voters=voters
        ),
        out_shape=[
            jax.ShapeDtypeStruct((K,), i32),
            jax.ShapeDtypeStruct((K, n), i32),
            jax.ShapeDtypeStruct((B,), i32),
            jax.ShapeDtypeStruct((B,), i32),
            jax.ShapeDtypeStruct((B,), jnp.bool_),
            jax.ShapeDtypeStruct((1,), i32),
        ],
        input_output_aliases={0: 0, 1: 1},
        interpret=_interpret(),
    )(prior, frontier, key, min_clock)
    return out[0], out[1], out[2], out[3], out[4], out[5][0]


# the Pallas twins join the compiled-identity audit alongside their
# composed counterparts: a canonicalized sweep holds EITHER route to one
# compile per program
from fantoch_tpu.core.compile_cache import register_program  # noqa: E402

register_program("pred_plane_step_pallas", pred_plane_step_pallas)
register_program("graph_plane_step_pallas", graph_plane_step_pallas)
register_program("votes_commit_pallas", votes_commit_pallas)
register_program("table_round_pallas", table_round_pallas)
