"""Device kernel for Caesar's two-phase predecessor ordering.

Reference: fantoch_ps/src/executor/pred/mod.rs:132-186 — a committed
command executes after (phase 1) every dependency is committed and
(phase 2) every LOWER-clock dependency is executed.  Timestamps are
unique and totally ordered, so there are no cycles to collapse; the host
twin (fantoch_tpu/executor/pred.py) maintains the two phases as
per-vertex countdown counters fed by pending indexes.

The device formulation batches both countdowns: dependencies are an
``int32[B, W]`` slot matrix (row indices into the batch, ``TERMINAL`` for
already-executed/absent deps, ``MISSING`` for uncommitted ones), and one
``lax.while_loop`` executes the monotone fixpoint

    executable(v) = committed(v) and for every dep slot d of v:
                      d is TERMINAL, or executed(d), or clock(d) > clock(v)

— each iteration is one scatter-free vectorized pass (the countdown
decrements of the host twin become a masked ``all`` over the dep matrix),
and at least one clock-minimal executable vertex finalizes per iteration,
so ``B`` iterations bound the loop; the early-exit fires as soon as a
pass makes no progress (missing-blocked residue waits for a later batch).

Output order is (clock, dot)-sorted among the executed — exactly the
commit-timestamp order the PredecessorsExecutor promises for conflicts.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL


class PredResolution(NamedTuple):
    order: jax.Array  # int32[B] — executed rows first, (clock, dot) sorted
    executed: jax.Array  # bool[B]


@jax.jit
def resolve_pred(
    deps: jax.Array,  # int32[B, W] row indices / TERMINAL / MISSING
    clock: jax.Array,  # int32[B] — committed timestamp (unique with dot)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    committed: jax.Array,  # bool[B] — False rows are pads / uncommitted
) -> PredResolution:
    batch, _width = deps.shape
    int_max = jnp.iinfo(jnp.int32).max
    safe = jnp.maximum(deps, 0)

    # phase 2's lower-clock comparison, precomputed per slot: a dep with a
    # HIGHER (clock, dot) never blocks (it executes after us)
    my_key = (clock, dot_src, dot_seq)
    dep_key = (clock[safe], dot_src[safe], dot_seq[safe])

    def lex_gt(a, b):
        """a > b on (clock, src, seq) triples, vectorized."""
        (ac, as_, aq), (bc, bs, bq) = a, b
        return (
            (ac > bc)
            | ((ac == bc) & (as_ > bs))
            | ((ac == bc) & (as_ == bs) & (aq > bq))
        )

    dep_higher = lex_gt(dep_key, tuple(k[:, None] for k in my_key))
    # a dep slot never blocks iff it is TERMINAL (already executed /
    # absent) or a COMMITTED dep with a higher (clock, dot) — phase 2
    # skips those.  An uncommitted dep's clock is meaningless (it may yet
    # commit lower), so MISSING and in-batch-uncommitted deps block
    # phase 1 outright.
    in_batch = deps >= 0
    dep_committed = in_batch & committed[safe]
    never_blocks = (deps == TERMINAL) | (dep_committed & dep_higher)

    def body(state):
        executed, _changed = state
        dep_ok = never_blocks | (dep_committed & executed[safe])
        new = committed & dep_ok.all(axis=1)
        changed = (new & ~executed).any()
        return new | executed, changed

    def cond(state):
        _executed, changed = state
        return changed

    executed0 = jnp.zeros((batch,), bool)
    first, changed0 = body((executed0, jnp.bool_(True)))
    executed, _ = jax.lax.while_loop(
        cond, body, (first, changed0)
    )
    sort_clock = jnp.where(executed, clock, int_max)
    order = jnp.lexsort((dot_seq, dot_src, sort_clock)).astype(jnp.int32)
    return PredResolution(order, executed)
