"""Device kernel for Caesar's two-phase predecessor ordering.

Reference: fantoch_ps/src/executor/pred/mod.rs:132-186 — a committed
command executes after (phase 1) every dependency is committed and
(phase 2) every LOWER-clock dependency is executed.  Timestamps are
unique and totally ordered, so there are no cycles to collapse; the host
twin (fantoch_tpu/executor/pred.py) maintains the two phases as
per-vertex countdown counters fed by pending indexes.

The device formulation batches both countdowns: dependencies are an
``int32[B, W]`` slot matrix (row indices into the batch, ``TERMINAL`` for
already-executed/absent deps, ``MISSING`` for uncommitted ones), and one
``lax.while_loop`` executes the monotone fixpoint

    executable(v) = committed(v) and for every dep slot d of v:
                      d is TERMINAL, or executed(d), or clock(d) > clock(v)

— each iteration is one scatter-free vectorized pass (the countdown
decrements of the host twin become a masked ``all`` over the dep matrix),
and at least one clock-minimal executable vertex finalizes per iteration,
so ``B`` iterations bound the loop; the early-exit fires as soon as a
pass makes no progress (missing-blocked residue waits for a later batch).

Output order is (clock, dot)-sorted among the executed — exactly the
commit-timestamp order the PredecessorsExecutor promises for conflicts.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from fantoch_tpu.core.compile_cache import register_program
from fantoch_tpu.ops.graph_resolve import MISSING, TERMINAL


class PredResolution(NamedTuple):
    order: jax.Array  # int32[B] — executed rows first, (clock, dot) sorted
    executed: jax.Array  # bool[B]


@jax.jit
def resolve_pred(
    deps: jax.Array,  # int32[B, W] row indices / TERMINAL / MISSING
    clock: jax.Array,  # int32[B] — committed timestamp (unique with dot)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    committed: jax.Array,  # bool[B] — False rows are pads / uncommitted
) -> PredResolution:
    batch, _width = deps.shape
    int_max = jnp.iinfo(jnp.int32).max
    safe = jnp.maximum(deps, 0)

    # phase 2's lower-clock comparison, precomputed per slot: a dep with a
    # HIGHER (clock, dot) never blocks (it executes after us)
    my_key = (clock, dot_src, dot_seq)
    dep_key = (clock[safe], dot_src[safe], dot_seq[safe])

    def lex_gt(a, b):
        """a > b on (clock, src, seq) triples, vectorized."""
        (ac, as_, aq), (bc, bs, bq) = a, b
        return (
            (ac > bc)
            | ((ac == bc) & (as_ > bs))
            | ((ac == bc) & (as_ == bs) & (aq > bq))
        )

    dep_higher = lex_gt(dep_key, tuple(k[:, None] for k in my_key))
    # a dep slot never blocks iff it is TERMINAL (already executed /
    # absent) or a COMMITTED dep with a higher (clock, dot) — phase 2
    # skips those.  An uncommitted dep's clock is meaningless (it may yet
    # commit lower), so MISSING and in-batch-uncommitted deps block
    # phase 1 outright.
    in_batch = deps >= 0
    dep_committed = in_batch & committed[safe]
    never_blocks = (deps == TERMINAL) | (dep_committed & dep_higher)

    def body(state):
        executed, _changed = state
        dep_ok = never_blocks | (dep_committed & executed[safe])
        new = committed & dep_ok.all(axis=1)
        changed = (new & ~executed).any()
        return new | executed, changed

    def cond(state):
        _executed, changed = state
        return changed

    executed0 = jnp.zeros((batch,), bool)
    first, changed0 = body((executed0, jnp.bool_(True)))
    executed, _ = jax.lax.while_loop(
        cond, body, (first, changed0)
    )
    sort_clock = jnp.where(executed, clock, int_max)
    order = jnp.lexsort((dot_seq, dot_src, sort_clock)).astype(jnp.int32)
    return PredResolution(order, executed)


# ---------------------------------------------------------------------------
# resident plane step (executor/pred_plane.DevicePredPlane)
# ---------------------------------------------------------------------------


class PredPlaneStep(NamedTuple):
    """One resident dispatch's output: the donated state back, plus which
    slots executed THIS dispatch.  Execution order among the newly
    executed is (clock, src) — computed HOST-side from the plane's slot
    columns (a dynamic-size host lexsort over the executed handful beats
    a full-capacity device sort every dispatch)."""

    deps: jax.Array  # int32[C, W] — resident slot matrix (donated through)
    clock: jax.Array  # int32[C]
    src: jax.Array  # int32[C]
    occ: jax.Array  # bool[C] — slot holds a committed command
    executed: jax.Array  # bool[C]
    newly: jax.Array  # bool[C] — executed by this dispatch


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))
def resolve_pred_plane_step_xla(
    deps: jax.Array,  # int32[C, W] slot indices / TERMINAL / MISSING
    clock: jax.Array,  # int32[C] — committed timestamp seq
    src: jax.Array,  # int32[C] — timestamp process id (clock uniqueness)
    occ: jax.Array,  # bool[C]
    executed: jax.Array,  # bool[C]
    u_row: jax.Array,  # int32[U] — new slot ids (pad = C, dropped)
    u_deps: jax.Array,  # int32[U, W]
    u_clock: jax.Array,  # int32[U]
    u_src: jax.Array,  # int32[U]
    p_row: jax.Array,  # int32[P] — dep-patch cells (pad = C, dropped)
    p_col: jax.Array,  # int32[P]
    p_val: jax.Array,  # int32[P] — slot id or TERMINAL
) -> PredPlaneStep:
    """The resident twin of :func:`resolve_pred` (executor/pred_plane.py).

    The whole pending window lives ON DEVICE across dispatches: ``C``
    slots of (deps, clock, src) with occupancy and executed flags, all
    donated in-place.  Each dispatch (1) installs the batch's new rows,
    (2) re-points dep cells whose missing dot just committed (the
    residual re-feed: missing-blocked rows stay resident and wake when a
    later feed patches them — the pred-plane analog of the table plane's
    beyond-gap runs), then (3) runs the same monotone two-phase fixpoint
    as :func:`resolve_pred` over the *entire* resident window, so rows
    blocked across any number of earlier feeds execute the moment their
    chain completes.

    Slot recycling is host-owned: a freed slot is simply overwritten by a
    later ``u_row`` install (occ/executed/clock/deps all re-set), so no
    clear pass is needed — the host only frees a slot once nothing
    references it.
    """
    cap, _width = deps.shape

    # (1) new rows: full-row install (reused slots are fully overwritten)
    deps = deps.at[u_row].set(u_deps, mode="drop")
    clock = clock.at[u_row].set(u_clock, mode="drop")
    src = src.at[u_row].set(u_src, mode="drop")
    occ = occ.at[u_row].set(True, mode="drop")
    executed = executed.at[u_row].set(False, mode="drop")
    # (2) dep patches: MISSING cells whose dot just committed (or was
    # recovered as a noop -> TERMINAL)
    deps = deps.at[p_row, p_col].set(p_val, mode="drop")

    # (3) fixpoint: executable(v) = occ(v) and every dep slot is
    # TERMINAL, executed, or a committed dep with a higher (clock, src)
    # key (phase 2's lower-clock rule; MISSING always blocks phase 1)
    in_res = deps >= 0
    safe = jnp.maximum(deps, 0)
    dep_clock, dep_src = clock[safe], src[safe]
    my_clock, my_src = clock[:, None], src[:, None]
    dep_higher = (dep_clock > my_clock) | (
        (dep_clock == my_clock) & (dep_src > my_src)
    )
    never_blocks = (deps == TERMINAL) | (in_res & occ[safe] & dep_higher)
    executed0 = executed

    def body(state):
        done, _changed = state
        dep_ok = never_blocks | (in_res & done[safe])
        new = occ & dep_ok.all(axis=1)
        changed = (new & ~done).any()
        return new | done, changed

    def cond(state):
        _done, changed = state
        return changed

    first, changed0 = body((executed0, jnp.bool_(True)))
    done, _ = jax.lax.while_loop(cond, body, (first, changed0))

    newly = done & ~executed0
    return PredPlaneStep(deps, clock, src, occ, done, newly)


register_program("pred_plane_step_xla", resolve_pred_plane_step_xla)
register_program("pred_resolve", resolve_pred)


def resolve_pred_plane_step(
    deps, clock, src, occ, executed,
    u_row, u_deps, u_clock, u_src, p_row, p_col, p_val,
) -> PredPlaneStep:
    """Route one resident pred-plane dispatch: the Pallas-fused kernel
    when :func:`fantoch_tpu.ops.pallas_resolve.pallas_enabled` says so
    (and the window fits VMEM), else the composed
    :func:`resolve_pred_plane_step_xla`.  Same signature, donation set,
    and bit-for-bit output either way — executors, twin replay, and
    shadow checks all call through here, so every consumer follows one
    route."""
    from fantoch_tpu.ops import pallas_resolve as pr

    args = (deps, clock, src, occ, executed,
            u_row, u_deps, u_clock, u_src, p_row, p_col, p_val)
    if pr.pallas_enabled() and pr._fits_vmem(deps, clock, src, u_deps):
        return pr.route_dispatch(
            "pred_plane_step", pr.pred_plane_step_pallas,
            resolve_pred_plane_step_xla, args, {},
        )
    return resolve_pred_plane_step_xla(*args)
