"""Executed-clock frontier with vectorized batch operations.

The device/array mirror of ``AEClock`` (fantoch_tpu/core/clocks.py) that
the reference keeps as ``Executed = AEClock<ProcessId>``
(fantoch/src/protocol/mod.rs:40) and consults per-dependency inside the
Tarjan walk (fantoch_ps/src/executor/graph/tarjan.rs:131-136).

Representation: per-source contiguous watermark (``seq <= watermark[src]``
=> executed) plus a single sorted array of packed above-watermark
exceptions (``src << 32 | seq``).  Both membership tests and adds are
numpy-vectorized over whole batches, which is what kills the per-dep
Python ``executed_clock.contains`` calls flagged in VERDICT r2 weak #2 /
missing #7; the scalar ``add``/``contains`` keep AEClock compatibility for
the host Tarjan oracle's stuck-residue walks.

``watermarks()``/``exceptions()`` expose the dense arrays for device use
(e.g. shipping the frontier into a jitted resolve as int64 operands).
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

_SEQ_BITS = 32
_SEQ_MASK = (1 << _SEQ_BITS) - 1


def pack_dots(src: np.ndarray, seq: np.ndarray) -> np.ndarray:
    """(source, sequence) -> single sortable int64 per dot."""
    return (src.astype(np.int64) << _SEQ_BITS) | seq.astype(np.int64)


class DeviceFrontier:
    """Vectorized executed-dot set over a fixed universe of process ids."""

    __slots__ = (
        "_max_id", "_watermark", "_exceptions", "_dirty", "_chunks", "_clean"
    )

    def __init__(self, process_ids: Iterable[int]):
        ids = list(process_ids)
        assert ids and min(ids) >= 0
        self._max_id = max(ids)
        # dense by process id (ids are small: shard*n+1..): O(max_id) memory
        self._watermark = np.zeros(self._max_id + 1, dtype=np.int64)
        self._exceptions = np.empty(0, dtype=np.int64)  # sorted packed dots
        self._dirty: List[int] = []  # unsorted packed scalar adds
        self._chunks: List[np.ndarray] = []  # whole-batch adds, uncompacted
        self._clean = True  # one compact pass is a fixpoint until new adds

    def _ensure(self, source: int) -> None:
        """Grow the dense watermark vector for an unseen source (AEClock
        accepts any actor; dots from not-yet-discovered processes must not
        crash the frontier)."""
        if source > self._max_id:
            grown = np.zeros(source + 1, dtype=np.int64)
            grown[: self._max_id + 1] = self._watermark
            self._watermark = grown
            self._max_id = source

    # --- scalar AEClock-compatible API (host Tarjan oracle) ---

    def add(self, source: int, sequence: int) -> bool:
        if self.contains(source, sequence):
            return False
        self._dirty.append((int(source) << _SEQ_BITS) | int(sequence))
        self._clean = False
        if len(self._dirty) >= 1024:
            self._compact()
        return True

    def contains(self, source: int, sequence: int) -> bool:
        self._ensure(source)
        if self._chunks:
            self._compact()
        if sequence <= self._watermark[source]:
            return True
        packed = (int(source) << _SEQ_BITS) | int(sequence)
        if self._dirty and packed in self._dirty:
            return True
        i = np.searchsorted(self._exceptions, packed)
        return bool(i < len(self._exceptions) and self._exceptions[i] == packed)

    def add_range(self, source: int, start: int, end: int) -> None:
        seqs = np.arange(start, end + 1, dtype=np.int64)
        self.add_batch(np.full(len(seqs), source, dtype=np.int64), seqs)

    # --- vectorized batch API ---

    def contains_batch(self, src: np.ndarray, seq: np.ndarray) -> np.ndarray:
        """bool[len(src)]: which (src, seq) dots are executed."""
        if len(src):
            self._ensure(int(np.max(src)))
        self._compact()
        below = seq <= self._watermark[src]
        if len(self._exceptions) == 0:
            return below
        packed = pack_dots(src, seq)
        i = np.searchsorted(self._exceptions, packed)
        i = np.minimum(i, len(self._exceptions) - 1)
        return below | (self._exceptions[i] == packed)

    def add_batch(self, src: np.ndarray, seq: np.ndarray) -> None:
        """Whole-batch add: stored as an uncompacted chunk; compaction is
        lazy (first read), so back-to-back batch adds pay one merge."""
        if len(src) == 0:
            return
        self._ensure(int(np.max(src)))
        self._chunks.append(pack_dots(src, seq))
        self._clean = False

    def _compact(self) -> None:
        """Merge dirty adds into the sorted exception array, then advance
        watermarks over contiguous runs and drop covered exceptions."""
        if self._clean:
            return
        self._clean = True
        if self._dirty or self._chunks:
            fresh = self._chunks
            if self._dirty:
                fresh = fresh + [np.array(self._dirty, dtype=np.int64)]
            self._dirty = []
            self._chunks = []
            merged = np.concatenate([self._exceptions, *fresh])
            self._exceptions = np.unique(merged)  # sort + dedupe
        if len(self._exceptions) == 0:
            return
        exc = self._exceptions
        src = (exc >> _SEQ_BITS).astype(np.int64)
        seq = (exc & _SEQ_MASK).astype(np.int64)
        # already-covered exceptions (watermark advanced past them)
        alive = seq > self._watermark[src]
        if not alive.all():
            exc, src, seq = exc[alive], src[alive], seq[alive]
        # contiguity: within each source's sorted run, an exception extends
        # the watermark iff seq == watermark + (position in run) + 1; a
        # prefix-sum formulation: rank-in-run r, candidate = watermark[src]
        # + r + 1; the run of consumable events is the maximal prefix with
        # seq == candidate.
        if len(exc):
            run_first = np.ones(len(exc), dtype=bool)
            run_first[1:] = src[1:] != src[:-1]
            run_start = np.maximum.accumulate(
                np.where(run_first, np.arange(len(exc)), 0)
            )
            rank = np.arange(len(exc)) - run_start
            candidate = self._watermark[src] + rank + 1
            is_step = seq == candidate
            # a gap breaks the rest of the run: prefix-and within runs
            run_broken = np.maximum.accumulate(
                np.where(~is_step, np.arange(len(exc)), -1)
            )
            consumable = is_step & (run_broken < run_start)
            if consumable.any():
                np.maximum.at(self._watermark, src[consumable], seq[consumable])
                exc = exc[~consumable]
        self._exceptions = exc

    # --- device-facing views ---

    def watermarks(self) -> np.ndarray:
        """int64[max_id + 1] contiguous frontier per source."""
        self._compact()
        return self._watermark.copy()

    def exceptions(self) -> np.ndarray:
        """Sorted int64 packed dots above the watermark."""
        self._compact()
        return self._exceptions.copy()

    def frontier_of(self, source: int) -> int:
        self._compact()
        return int(self._watermark[source])

    def event_count(self) -> int:
        self._compact()
        return int(self._watermark.sum()) + len(self._exceptions)
