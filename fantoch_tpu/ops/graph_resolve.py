"""Batched dependency-graph resolution on TPU — the north-star kernel.

Replaces the reference's serial Tarjan walk
(fantoch_ps/src/executor/graph/tarjan.rs:99-319) with a data-parallel
resolver over a batch of committed commands.  The output contract is the
one the reference's correctness argument actually needs (see
fantoch/src/executor/monitor.rs and the sim_test agreement check
fantoch_ps/src/protocol/mod.rs:924-1010):

  * members of one SCC execute contiguously, ordered by dot
    (tarjan.rs:15 — ``SCC = BTreeSet<Dot>``);
  * if SCC A depends on SCC B, then B executes before A (topological
    order of the condensation);
  * independent SCCs may execute in any order (they share no keys, since
    conflicting commands are always linked by dependencies), so only
    *local* topological validity is required — no cross-process rank
    agreement.

Representation (device arrays over a batch of B command slots):

  * ``dep[B]`` (functional path) or ``deps[B, D]`` (general path): batch
    index of each dependency after pruning, with sentinels
    ``TERMINAL = -1`` (no dependency / dependency already executed) and
    ``MISSING = -2`` (dependency not yet committed here — the vertex and
    everything that reaches it stays unresolved, mirroring the pending
    index in fantoch_ps/src/executor/graph/index.rs:146).
  * dots are carried as ``(dot_src[B], dot_seq[B])`` int32 pairs for the
    intra-SCC sort.

Why a functional fast path: with the reference's sequential ``KeyDeps``
(fantoch_ps/src/protocol/common/graph/deps/keys/sequential.rs:8-11) each
command picks up exactly one dependency per key — the latest.  A batch of
single-key commands therefore forms a *functional graph* (out-degree <= 1)
whose weakly-connected components are rho-shapes: cycles can only sit at
the oldest end of a chain (a mid-chain cycle would need out-degree 2).
Functional graphs admit an **exact O(log B)** resolution with pointer
doubling:

  1. doubling with distance accumulation ranks every chain (list ranking);
  2. min-id accumulation along the jumped path identifies each cycle's
     leader exactly (a 2^L >= 2B hop walk from any non-terminating vertex
     wraps its cycle completely);
  3. a binary-closure scatter from the leaders marks cycle membership;
  4. a second doubling pass ranks the vertices that flow into cycles.

Everything is gathers/scatters/min/max over int32[B] — no data-dependent
shapes, fully jittable, MXU-free but HBM-friendly.  The general
(multi-key, out-degree D) path uses affine-max pointer doubling with a
relaxation floor; the rare residue it cannot finish (3+-cycles) is
reported via ``stuck`` so the caller can hand those vertices to the host
Tarjan oracle (executor/graph/deps_graph.py).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

TERMINAL = -1  # dependency executed / absent (pruned)
MISSING = -2  # dependency not committed here yet: blocks resolution

# rank assigned to unresolved vertices so they sort after all resolved ones
_UNRESOLVED_RANK = jnp.iinfo(jnp.int32).max


class Resolution(NamedTuple):
    """Result of one batched resolve.

    ``order`` is a permutation of batch indices: resolved vertices first in
    execution order, unresolved vertices at the tail (use ``resolved`` to
    cut).  ``rank``/``leader`` expose the condensation structure for tests.
    """

    order: jax.Array  # int32[B] permutation
    resolved: jax.Array  # bool[B]
    rank: jax.Array  # int32[B] topological level (condensation)
    leader: jax.Array  # int32[B] SCC leader (batch index)
    on_cycle: jax.Array  # bool[B]


def _num_doubling_steps(batch: int) -> int:
    """Steps so that 2^L >= 2*batch: a walk of 2^L hops from any vertex of a
    non-terminating component has fully wrapped its cycle at least once."""
    steps = 1
    while (1 << steps) < 2 * max(batch, 2):
        steps += 1
    return steps


@functools.partial(jax.jit, static_argnames=("return_order",))
def resolve_functional(
    dep: jax.Array,  # int32[B] — single dependency (TERMINAL/MISSING sentinels)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    return_order: bool = True,
) -> Resolution:
    """Exact batched resolution of an out-degree-<=1 dependency graph."""
    batch = dep.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)
    steps = _num_doubling_steps(batch)

    is_term = dep == TERMINAL
    is_miss = dep == MISSING
    absorbing = is_term | is_miss

    # self-absorbing pointers: terminals/missing point at themselves with
    # zero step cost, so doubling past them is a no-op.
    jump = jnp.where(absorbing, idx, dep)
    # min id over the true path p^1..p^(2^t); init = id of first hop
    acc = jnp.where(absorbing, jnp.int32(batch), jump)

    jumps_log = []  # p^(2^t) for the closure scatter below
    for _ in range(steps):
        jumps_log.append(jump)
        acc = jnp.minimum(acc, acc[jump])
        jump = jump[jump]

    end = jump  # endpoint after 2^steps hops
    end_term = is_term[end]
    end_miss = is_miss[end]
    nonterminating = ~(end_term | end_miss)

    # --- cycles: every non-terminating walk has wrapped its cycle, so the
    # path-min at the endpoint is exactly the cycle's smallest id.
    cyc_leader = acc[end]
    # seeds: the leaders themselves are cycle members by construction
    on_cycle = nonterminating & (idx == cyc_leader)
    # binary closure along p: orbit of each leader = its whole cycle (p maps
    # cycle members to cycle members, so marks cannot leak off the cycle).
    for hop in jumps_log:
        contrib = jnp.zeros_like(on_cycle).at[hop].max(on_cycle)
        on_cycle = on_cycle | (contrib & nonterminating)

    # --- second doubling pass: rank = distance to a terminal or to the
    # cycle boundary (cycle members themselves sit at rank 0 of their
    # component, which is all local topological validity requires).
    absorbing2 = absorbing | on_cycle
    jump2 = jnp.where(absorbing2, idx, dep)
    dist2 = jnp.where(absorbing2, 0, 1).astype(jnp.int32)
    for _ in range(steps):
        dist2 = dist2 + dist2[jump2]
        jump2 = jump2[jump2]

    resolved = jnp.where(on_cycle, True, is_term[jump2] | on_cycle[jump2])
    rank = jnp.where(resolved, dist2, _UNRESOLVED_RANK).astype(jnp.int32)
    leader = jnp.where(on_cycle, cyc_leader, idx).astype(jnp.int32)

    if not return_order:
        order = idx
    else:
        order = _order_from_ranks(rank, leader, dot_src, dot_seq)
    return Resolution(order, resolved, rank, leader, on_cycle)


def _order_from_ranks(rank, leader, dot_src, dot_seq) -> jax.Array:
    """Execution order: (rank, SCC leader, dot) lexicographic.

    Same-SCC members share (rank, leader) and are therefore contiguous and
    dot-sorted (the reference's BTreeSet<Dot> order, tarjan.rs:15).  The
    rank key makes every SCC follow all SCCs it depends on.  Unresolved
    vertices carry rank INT32_MAX and sink to the tail.
    """
    return jnp.lexsort((dot_seq, dot_src, leader, rank)).astype(jnp.int32)


# ---------------------------------------------------------------------------
# general path: out-degree up to D (multi-key commands)
# ---------------------------------------------------------------------------


class GeneralResolution(NamedTuple):
    order: jax.Array  # int32[B]
    resolved: jax.Array  # bool[B]
    rank: jax.Array  # int32[B]
    leader: jax.Array  # int32[B]
    stuck: jax.Array  # bool[B] — not resolved and not missing-blocked:
    # cycles the device pass could not collapse; host oracle finishes them.


@functools.partial(jax.jit, static_argnames=("max_iters",))
def resolve_general(
    deps: jax.Array,  # int32[B, D]
    dot_src: jax.Array,
    dot_seq: jax.Array,
    *,
    max_iters: int = 0,  # 0 -> auto: 4 * log2(B) + 8
) -> GeneralResolution:
    """Batched resolution for out-degree-D graphs.

    Affine-max pointer doubling: each dependency slot of vertex v is a
    constraint ``rank[v] >= max(floor, add + rank[target])``.  A slot whose
    target has finalized folds into the floor; a slot whose target has
    exactly one live slot composes through it (chain doubling); any live
    target always contributes its current floor (monotone relaxation), so
    progress never stalls on merge vertices — worst case degrades to
    frontier peeling, typical per-key-chain graphs finish in O(log depth).

    SCCs whose vertices are connected by *mutual* edges (the dominant
    shape: k concurrent conflicting proposals that all saw each other,
    k = 2 being two replicas racing) are collapsed exactly by a
    mutual-edge connected-components pre-pass.  Cycles with no mutual
    edges (delivery orders where conflict visibility is strictly
    one-directional around a ring) surface as ``stuck`` for the host
    Tarjan oracle to finish — they cannot deadlock or spin the device
    pass: floors/adds saturate at the batch size, after which the loop
    settles and the budget check exits early.
    """
    batch, width = deps.shape
    idx = jnp.arange(batch, dtype=jnp.int32)
    if max_iters == 0:
        max_iters = 4 * _num_doubling_steps(batch) + 8

    # --- mutual-edge SCC collapse: v and u mutually dependent -> same SCC,
    # and so is the whole connected component of the (undirected) mutual-
    # edge graph.  leader = min id of the component, found by min-label
    # propagation over mutual neighbours with pointer jumping; intra-
    # component edges are pruned and inbound edges retargeted.
    tgt = deps  # int32[B, D]
    valid = tgt >= 0
    safe_tgt = jnp.where(valid, tgt, 0)
    # reverse test: does any slot of target point back at v?
    back = (tgt[safe_tgt] == idx[:, None, None]).any(axis=-1) & valid
    leader = idx
    for _ in range(_num_doubling_steps(batch)):
        # min over mutual neighbours' leaders, then pointer jump
        nbr_min = jnp.where(back, leader[safe_tgt], jnp.int32(batch)).min(axis=-1)
        leader = jnp.minimum(leader, nbr_min)
        leader = jnp.minimum(leader, leader[leader])

    # rewrite deps through leaders; drop intra-SCC edges
    tgt = jnp.where(valid, leader[safe_tgt], tgt)
    tgt = jnp.where(valid & (tgt == leader[:, None]), TERMINAL, tgt)
    # non-leaders hand their external deps to... they keep them: every
    # member's constraints apply to the SCC; members share the leader's
    # rank at the end, so fold member floors via a segment-max on leader.

    is_miss = tgt == MISSING
    add = jnp.where(tgt >= 0, 1, 0).astype(jnp.int32)
    floor = jnp.zeros((batch, width), dtype=jnp.int32)
    missing_blocked = is_miss.any(axis=-1)

    member_count = jnp.zeros(batch, jnp.int32).at[leader].add(1)

    def body(state):
        it, tgt, add, floor, missing_blocked, _changed = state
        # a slot that composed all the way around a 3+-cycle points at its
        # own vertex: frozen — excluded from folding, absorption and
        # composition so the loop settles and the budget exits early; the
        # vertex stays live and surfaces as ``stuck``.
        frozen = tgt == idx[:, None]
        live = (tgt >= 0) & ~frozen
        safe = jnp.where(live, tgt, 0)
        n_live = live.sum(axis=-1)  # live slots per vertex row
        vfloor = floor.max(axis=-1)  # row lower bound

        # SCC-aggregate view (live targets are always leaders): a slot on a
        # multi-member SCC must fold the *aggregate* rank and wait for all
        # members, or dependents would undercut 1 + scc_rank.
        agg_floor = jnp.zeros(batch, jnp.int32).at[leader].max(vfloor)
        agg_live = jnp.zeros(batch, jnp.int32).at[leader].add(n_live)
        agg_miss = jnp.zeros(batch, bool).at[leader].max(missing_blocked)
        agg_frozen = jnp.zeros(batch, bool).at[leader].max(frozen.any(axis=-1))
        agg_final = (agg_live == 0) & ~agg_miss & ~agg_frozen

        t_final = agg_final[safe]
        t_miss = agg_miss[safe]
        t_vfloor = agg_floor[safe]

        # (a) finalized target SCC: fold into floor, close the slot
        new_floor = jnp.where(live & t_final, jnp.maximum(floor, add + t_vfloor), floor)
        new_tgt = jnp.where(live & t_final, TERMINAL, tgt)
        new_add = add

        # (b) missing-blocked target: vertex becomes missing-blocked
        new_missing = missing_blocked | (live & t_miss).any(axis=-1)

        # (c) live target: always absorb its floor (relaxation)...
        still = live & ~t_final & ~t_miss
        new_floor = jnp.where(still, jnp.maximum(new_floor, add + t_vfloor), new_floor)
        # ...and compose through singleton-SCC targets with one live slot
        # (chain doubling); stop composing once ``add`` saturates — a legit
        # chain has < batch hops, so only unwrapped cycles ever get there.
        single = (
            still
            & (agg_live[safe] == 1)
            & (member_count[safe] == 1)
            & (add < jnp.int32(batch))
        )
        t_live = ((tgt >= 0) & ~frozen)[safe]  # [B, D, D]
        slot_of_t = jnp.argmax(t_live, axis=-1)  # [B, D]
        t_slot_tgt = jnp.take_along_axis(tgt[safe], slot_of_t[..., None], axis=-1)[..., 0]
        t_slot_add = jnp.take_along_axis(add[safe], slot_of_t[..., None], axis=-1)[..., 0]
        new_tgt = jnp.where(single, t_slot_tgt, new_tgt)
        new_add = jnp.where(single, add + t_slot_add, new_add)
        # a composition that lands on the vertex itself wrapped a cycle the
        # mutual-edge pass missed; it becomes ``frozen`` next iteration

        # saturate: legitimate ranks/hop-counts are < batch, so capping at
        # batch only affects un-collapsible cycles — whose floors would
        # otherwise grow (and overflow) forever, keeping ``changed`` true
        # for the whole budget instead of settling in O(log batch) rounds.
        new_floor = jnp.minimum(new_floor, jnp.int32(batch))
        new_add = jnp.minimum(new_add, jnp.int32(batch))

        changed = (
            (new_tgt != tgt).any() | (new_floor != floor).any() | (new_missing != missing_blocked).any()
        )
        return it + 1, new_tgt, new_add, new_floor, new_missing, changed

    def cond(state):
        it, _tgt, _add, _floor, _miss, changed = state
        return (it < max_iters) & changed

    state = (jnp.int32(0), tgt, add, floor, missing_blocked, jnp.bool_(True))
    _, tgt, add, floor, missing_blocked, _ = jax.lax.while_loop(cond, body, state)

    live = tgt >= 0
    final = (live.sum(axis=-1) == 0) & ~missing_blocked
    vrank = floor.max(axis=-1)

    # fold SCC members onto their leader: shared rank = max member rank
    scc_rank = jnp.zeros(batch, jnp.int32).at[leader].max(jnp.where(final, vrank, 0))
    scc_final = jnp.ones(batch, bool).at[leader].min(final)
    scc_missing = jnp.zeros(batch, bool).at[leader].max(missing_blocked)
    resolved = scc_final[leader] & ~scc_missing[leader]
    rank = jnp.where(resolved, scc_rank[leader], _UNRESOLVED_RANK).astype(jnp.int32)
    stuck = ~resolved & ~(missing_blocked | scc_missing[leader])

    order = _order_from_ranks(rank, leader, dot_src, dot_seq)
    return GeneralResolution(order, resolved, rank, leader, stuck)
