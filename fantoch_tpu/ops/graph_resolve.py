"""Batched dependency-graph resolution on TPU — the north-star kernel.

Replaces the reference's serial Tarjan walk
(fantoch_ps/src/executor/graph/tarjan.rs:99-319) with a data-parallel
resolver over a batch of committed commands.  The output contract is the
one the reference's correctness argument actually needs (see
fantoch/src/executor/monitor.rs and the sim_test agreement check
fantoch_ps/src/protocol/mod.rs:924-1010):

  * members of one SCC execute contiguously, ordered by dot
    (tarjan.rs:15 — ``SCC = BTreeSet<Dot>``);
  * if SCC A depends on SCC B, then B executes before A (topological
    order of the condensation);
  * independent SCCs may execute in any order (they share no keys, since
    conflicting commands are always linked by dependencies), so only
    *local* topological validity is required — no cross-process rank
    agreement.

Representation (device arrays over a batch of B command slots):

  * ``dep[B]`` (functional path) or ``deps[B, D]`` (general path): batch
    index of each dependency after pruning, with sentinels
    ``TERMINAL = -1`` (no dependency / dependency already executed) and
    ``MISSING = -2`` (dependency not yet committed here — the vertex and
    everything that reaches it stays unresolved, mirroring the pending
    index in fantoch_ps/src/executor/graph/index.rs:146).
  * dots are carried as ``(dot_src[B], dot_seq[B])`` int32 pairs for the
    intra-SCC sort.

Why a functional fast path: with the reference's sequential ``KeyDeps``
(fantoch_ps/src/protocol/common/graph/deps/keys/sequential.rs:8-11) each
command picks up exactly one dependency per key — the latest.  A batch of
single-key commands therefore forms a *functional graph* (out-degree <= 1)
whose weakly-connected components are rho-shapes: cycles can only sit at
the oldest end of a chain (a mid-chain cycle would need out-degree 2).
Functional graphs admit an **exact O(log B)** resolution with pointer
doubling:

  1. doubling with distance accumulation ranks every chain (list ranking);
  2. min-id accumulation along the jumped path identifies each cycle's
     leader exactly (a 2^L >= 2B hop walk from any non-terminating vertex
     wraps its cycle completely);
  3. a binary-closure scatter from the leaders marks cycle membership;
  4. a second doubling pass ranks the vertices that flow into cycles.

Everything is gathers/scatters/min/max over int32[B] — no data-dependent
shapes, fully jittable, MXU-free but HBM-friendly.  The general
(multi-key, out-degree D) path uses affine-max pointer doubling with a
relaxation floor; the rare residue it cannot finish (3+-cycles) is
reported via ``stuck`` so the caller can hand those vertices to the host
Tarjan oracle (executor/graph/deps_graph.py).
"""

from __future__ import annotations

import contextlib
import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from fantoch_tpu.core.compile_cache import register_program

TERMINAL = -1  # dependency executed / absent (pruned)
MISSING = -2  # dependency not committed here yet: blocks resolution

# rank assigned to unresolved vertices so they sort after all resolved ones
_UNRESOLVED_RANK = jnp.iinfo(jnp.int32).max


class Resolution(NamedTuple):
    """Result of one batched resolve.

    ``order`` is a permutation of batch indices: resolved vertices first in
    execution order, unresolved vertices at the tail (use ``resolved`` to
    cut).  ``rank``/``leader`` expose the condensation structure for tests.
    """

    order: jax.Array  # int32[B] permutation
    resolved: jax.Array  # bool[B]
    rank: jax.Array  # int32[B] topological level (condensation)
    leader: jax.Array  # int32[B] SCC leader (batch index)
    on_cycle: jax.Array  # bool[B]


def _num_doubling_steps(batch: int) -> int:
    """Steps so that 2^L >= 2*batch: a walk of 2^L hops from any vertex of a
    non-terminating component has fully wrapped its cycle at least once."""
    steps = 1
    while (1 << steps) < 2 * max(batch, 2):
        steps += 1
    return steps


def _doubling_core(dep: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Pointer-doubling resolution core: (resolved, rank, leader, on_cycle).

    Exact for any out-degree-<=1 graph; O(log B) rounds of B-wide gathers.
    Shared by ``resolve_functional`` (full batch) and the keyed path's
    residual finish (small compacted batch, where the gathers are cheap).
    """
    batch = dep.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)
    steps = _num_doubling_steps(batch)

    is_term = dep == TERMINAL
    is_miss = dep == MISSING
    absorbing = is_term | is_miss

    # self-absorbing pointers: terminals/missing point at themselves with
    # zero step cost, so doubling past them is a no-op.
    jump = jnp.where(absorbing, idx, dep)
    # min id over the true path p^1..p^(2^t); init = id of first hop
    acc = jnp.where(absorbing, jnp.int32(batch), jump)

    jumps_log = []  # p^(2^t) for the closure scatter below
    for _ in range(steps):
        jumps_log.append(jump)
        acc = jnp.minimum(acc, acc[jump])
        jump = jump[jump]

    end = jump  # endpoint after 2^steps hops
    end_term = is_term[end]
    end_miss = is_miss[end]
    nonterminating = ~(end_term | end_miss)

    # --- cycles: every non-terminating walk has wrapped its cycle, so the
    # path-min at the endpoint is exactly the cycle's smallest id.
    cyc_leader = acc[end]
    # seeds: the leaders themselves are cycle members by construction
    on_cycle = nonterminating & (idx == cyc_leader)
    # binary closure along p: orbit of each leader = its whole cycle (p maps
    # cycle members to cycle members, so marks cannot leak off the cycle).
    for hop in jumps_log:
        contrib = jnp.zeros_like(on_cycle).at[hop].max(on_cycle)
        on_cycle = on_cycle | (contrib & nonterminating)

    # --- second doubling pass: rank = distance to a terminal or to the
    # cycle boundary (cycle members themselves sit at rank 0 of their
    # component, which is all local topological validity requires).
    absorbing2 = absorbing | on_cycle
    jump2 = jnp.where(absorbing2, idx, dep)
    dist2 = jnp.where(absorbing2, 0, 1).astype(jnp.int32)
    for _ in range(steps):
        dist2 = dist2 + dist2[jump2]
        jump2 = jump2[jump2]

    resolved = jnp.where(on_cycle, True, is_term[jump2] | on_cycle[jump2])
    rank = jnp.where(resolved, dist2, _UNRESOLVED_RANK).astype(jnp.int32)
    leader = jnp.where(on_cycle, cyc_leader, idx).astype(jnp.int32)
    return resolved, rank, leader, on_cycle


@functools.partial(jax.jit, static_argnames=("return_order",))
def resolve_functional(
    dep: jax.Array,  # int32[B] — single dependency (TERMINAL/MISSING sentinels)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    return_order: bool = True,
) -> Resolution:
    """Exact batched resolution of an out-degree-<=1 dependency graph."""
    resolved, rank, leader, on_cycle = _doubling_core(dep)
    if not return_order:
        order = jnp.arange(dep.shape[0], dtype=jnp.int32)
    else:
        order = _order_from_ranks(rank, leader, dot_src, dot_seq)
    return Resolution(order, resolved, rank, leader, on_cycle)


class KeyedResolution(NamedTuple):
    """Result of one keyed batched resolve (``resolve_functional_keyed``).

    ``order``/``resolved``/``rank``/``leader``/``on_cycle`` as in
    ``Resolution`` when ``return_structure=True``.  With
    ``return_structure=False`` (the latency-critical entry) ``resolved`` is
    a *permutation* of the true per-vertex flags — valid for reductions
    (``all``/``sum``) but not for indexing — and rank/leader/on_cycle are
    zeros; use ``n_resolved`` for counting.  ``overflow`` means the
    residual exceeded ``residual_size`` and the result must be discarded
    (the caller falls back to ``resolve_functional``).
    """

    order: jax.Array  # int32[B]
    resolved: jax.Array  # bool[B]
    rank: jax.Array  # int32[B]
    leader: jax.Array  # int32[B]
    on_cycle: jax.Array  # bool[B]
    n_resolved: jax.Array  # int32 scalar
    overflow: jax.Array  # bool scalar


def _residual_size_for(batch: int) -> int:
    """Default residual capacity: whole batch when small (tests — never
    overflow), B/64 when large (cycles + cross-replica chain inversions are
    a thin slice of real traffic; overflow falls back to full doubling)."""
    cap = batch if batch <= 4096 else max(4096, batch // 64)
    return _pow2_at_least(cap)


def _pow2_at_least(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.partial(jax.jit, static_argnames=("residual_size", "return_structure"))
def resolve_functional_keyed(
    key: jax.Array,  # int32[B] — conflict-key hash per command (perf hint)
    dep: jax.Array,  # int32[B]
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    residual_size: int,
    return_structure: bool = True,
) -> KeyedResolution:
    """Sort-based exact resolution of an out-degree-<=1 dependency graph.

    The north-star kernel (SURVEY §7 stage 4; VERDICT r2 item 1).  Replaces
    O(log B) rounds of B-wide random gathers (~6.6 ms each on TPU v5e at
    B=1M — the 894 ms of round 2) with a handful of B-wide *sorts*
    (~0.4-2 ms each) plus small-residual doubling:

      1. stable-sort the batch by key hash: each key's commands become one
         contiguous run in batch-arrival order;
      2. verify every in-run link: position p is *chain-verified* when its
         dep is exactly the previous in-run vertex and the run head's dep
         is TERMINAL.  For graphs produced by sequential KeyDeps in arrival
         order (the dominant shape — the file docstring's rho argument),
         every link verifies and the run position IS the rank;
      3. everything downstream of the first unverified link in a run (cycle
         heads, cross-replica chain inversions, missing-blocked suffixes)
         is compacted into a ``residual_size`` buffer and finished exactly
         by ``_doubling_core`` at residual scale, where gathers are cheap;
         deps that point back into a verified prefix fold to TERMINAL —
         sound because the whole prefix of that run is emitted first;
      4. residual vertices are re-emitted at their run's tail positions
         ((rank, SCC leader, dot) order within the run), and one final sort
         by (unresolved, emit position) yields ``order``.

    Exactness does not depend on the key hint: any link the sort order
    cannot verify lands in the residual and is resolved by doubling, so
    hash collisions and adversarial inputs only cost performance (worst
    case ``overflow`` → caller reruns via ``resolve_functional``).  The
    only structural requirement is the functional one (out-degree <= 1)
    plus deps linking same-key vertices (guaranteed: deps are conflicts —
    fantoch_ps/src/protocol/common/graph/deps/keys/sequential.rs:8-11);
    cross-key deps would break run locality and must go through
    ``resolve_general``.
    """
    batch = dep.shape[0]
    res_n = min(residual_size, batch)
    idx = jnp.arange(batch, dtype=jnp.int32)
    p_iota = idx

    # --- 1. one stable sort groups runs in arrival order
    k_s, pos_s, dep_s = jax.lax.sort(
        (key.astype(jnp.int32), idx, dep), num_keys=1, is_stable=True
    )

    # --- 2. link verification + prefix ranking (elementwise + cummax)
    head = jnp.concatenate([jnp.ones((1,), bool), k_s[1:] != k_s[:-1]])
    prev_pos = jnp.roll(pos_s, 1)  # head rows never read it
    ok = jnp.where(head, dep_s == TERMINAL, dep_s == prev_pos)
    run_start = jax.lax.cummax(jnp.where(head, p_iota, 0))
    lastbad = jax.lax.cummax(jnp.where(~ok, p_iota, -1))
    chain_ok = lastbad < run_start  # no unverified link in [run_start, p]
    rank_fast = p_iota - run_start

    cflag = chain_ok.astype(jnp.int32)
    n_residual = batch - cflag.sum()
    overflow = n_residual > res_n

    def _residual_path(structure: bool):
        """Compact + doubling + emit (stages 3-4).  Returns
        (order, unres_b) and, when ``structure``, per-vertex
        (rank_b, leader_b, cyc_b) in sorted space."""
        # --- 3. compact the residual (stable by cflag keeps run order)
        _, p_r_full = jax.lax.sort((cflag, p_iota), num_keys=1, is_stable=True)
        p_r = p_r_full[:res_n]  # sorted-space position of each residual row
        r_iota = jnp.arange(res_n, dtype=jnp.int32)
        valid_r = r_iota < n_residual
        # small gathers (res_n rows) pull the rest of the residual view
        rpos = pos_s[p_r]  # original batch index
        rdep = dep_s[p_r]
        rrs = jnp.where(valid_r, run_start[p_r], jnp.iinfo(jnp.int32).max)
        rsrc = dot_src[rpos]
        rseq = dot_seq[rpos]

        # remap deps to residual-local slots; deps leaving the residual (into
        # a verified prefix or already executed) fold to TERMINAL — the whole
        # prefix of the run is emitted before any residual member of it
        remap = jnp.full((batch,), TERMINAL, dtype=jnp.int32)
        remap = remap.at[jnp.where(valid_r, rpos, batch)].set(r_iota, mode="drop")
        rdep_local = jnp.where(
            rdep >= 0, remap[jnp.clip(rdep, 0, batch - 1)], rdep
        )
        rdep_local = jnp.where(valid_r, rdep_local, TERMINAL)

        # residual groups (per run) in p order: first residual row of a run
        # sits exactly at the run's first unverified position.  In p_r order
        # rrs is already sorted (run_start is monotone in p and compaction
        # is stable), so the emit sort below keeps every group's block at
        # the same offsets — per-group constants like firstbad carry over
        # elementwise without riding the sort.
        g_head = jnp.concatenate([jnp.ones((1,), bool), rrs[1:] != rrs[:-1]])
        firstbad = jax.lax.cummax(jnp.where(g_head, p_r, 0))

        # --- exact finish at residual scale
        l_resolved, l_rank, l_leader, l_on_cycle = _doubling_core(rdep_local)

        # emit order within each run's residual tail: resolved first, then
        # (rank, SCC leader, dot) — SCC members contiguous and dot-sorted
        l_unres = (~l_resolved).astype(jnp.int32)
        operands = [
            rrs,
            l_unres,
            l_rank,
            l_leader,
            rsrc,
            rseq,
            p_r,
            l_resolved.astype(jnp.int32),
        ]
        if structure:
            operands += [
                jnp.where(valid_r, l_rank, 0),
                rpos[jnp.clip(l_leader, 0, res_n - 1)],  # leader as orig index
                l_on_cycle.astype(jnp.int32),
            ]
        sorted_ops = jax.lax.sort(tuple(operands), num_keys=6, is_stable=True)
        e_p_r, e_res = sorted_ops[6], sorted_ops[7]
        emit_local = r_iota - jax.lax.cummax(jnp.where(g_head, r_iota, 0))
        target_r = firstbad + emit_local
        # invalid rows sank to the emit-sort tail (rrs=max) = exactly ~valid_r

        # --- 4. scatter residual emit data back over the batch, final sort
        # by one packed key: (unresolved << 30) | target position
        sc_idx = jnp.where(valid_r, e_p_r, batch)
        tgt_b = p_iota.at[sc_idx].set(target_r, mode="drop")
        unres_b = (~chain_ok).at[sc_idx].set(e_res == 0, mode="drop")
        packed = jnp.where(unres_b, jnp.int32(1) << 30, 0) | tgt_b
        _, order = jax.lax.sort((packed, pos_s), num_keys=1, is_stable=True)
        if not structure:
            return order, unres_b

        e_rank2, e_leader2, e_cyc = sorted_ops[8], sorted_ops[9], sorted_ops[10]
        rank_b = jnp.where(chain_ok, rank_fast, _UNRESOLVED_RANK)
        rank_b = rank_b.at[sc_idx].set(
            jnp.where(e_res == 1, firstbad - rrs + e_rank2, _UNRESOLVED_RANK),
            mode="drop",
        )
        leader_b = pos_s.at[sc_idx].set(e_leader2, mode="drop")
        cyc_b = jnp.zeros((batch,), jnp.int32).at[sc_idx].set(e_cyc, mode="drop")
        return order, unres_b, rank_b, leader_b, cyc_b

    if not return_structure:
        # latency-critical entry: when every link chain-verified (the
        # dominant shape — deps produced by latest-per-key KeyDeps in
        # arrival order) the run position IS the rank and the grouped order
        # is already the execution order; skip compaction + doubling + emit,
        # which at residual scale are pure op-launch overhead (~10 ms of the
        # round-2 kernel's 17 ms — scripts/profile_resolve.py).
        order, unres_b = jax.lax.cond(
            n_residual == 0,
            lambda: (pos_s, jnp.zeros((batch,), bool)),
            lambda: _residual_path(False),
        )
        n_resolved = (batch - unres_b.sum()).astype(jnp.int32)
        zeros = jnp.zeros((batch,), jnp.int32)
        return KeyedResolution(
            order, ~unres_b, zeros, zeros, zeros.astype(bool), n_resolved, overflow
        )

    order, unres_b, rank_b, leader_b, cyc_b = _residual_path(True)
    n_resolved = (batch - unres_b.sum()).astype(jnp.int32)

    # realign per-vertex structure to original batch order (one more sort)
    aligned = jax.lax.sort(
        (
            pos_s,
            (~unres_b).astype(jnp.int32),
            rank_b,
            leader_b,
            cyc_b,
        ),
        num_keys=1,
        is_stable=True,
    )
    _, a_res, a_rank, a_leader, a_cyc = aligned
    return KeyedResolution(
        order,
        a_res == 1,
        a_rank,
        a_leader,
        a_cyc.astype(bool),
        n_resolved,
        overflow,
    )


def _order_from_ranks(rank, leader, dot_src, dot_seq) -> jax.Array:
    """Execution order: (rank, SCC leader, dot) lexicographic.

    Same-SCC members share (rank, leader) and are therefore contiguous and
    dot-sorted (the reference's BTreeSet<Dot> order, tarjan.rs:15).  The
    rank key makes every SCC follow all SCCs it depends on.  Unresolved
    vertices carry rank INT32_MAX and sink to the tail.
    """
    return jnp.lexsort((dot_seq, dot_src, leader, rank)).astype(jnp.int32)


def resolve_keyed_auto(
    key: jax.Array,
    dep: jax.Array,
    dot_src: jax.Array,
    dot_seq: jax.Array,
    *,
    return_structure: bool = True,
) -> KeyedResolution:
    """Host wrapper over ``resolve_functional_keyed``: picks the default
    residual capacity and falls back to the exact full-batch doubling path
    if the residual overflows (one host sync either way — the caller
    fetches results right after)."""
    batch = dep.shape[0]
    res = resolve_functional_keyed(
        key,
        dep,
        dot_src,
        dot_seq,
        residual_size=_residual_size_for(batch),
        return_structure=return_structure,
    )
    if bool(res.overflow):
        full = resolve_functional(dep, dot_src, dot_seq)
        return KeyedResolution(
            full.order,
            full.resolved,
            full.rank,
            full.leader,
            full.on_cycle,
            full.resolved.sum().astype(jnp.int32),
            jnp.bool_(False),
        )
    return res


# ---------------------------------------------------------------------------
# general path: out-degree up to D (multi-key commands)
# ---------------------------------------------------------------------------


class GeneralResolution(NamedTuple):
    # jax.Array from the jitted resolvers; host np.ndarray from the
    # host-orchestrated resolve_general_staged (both index identically)
    order: jax.Array  # int32[B]
    resolved: jax.Array  # bool[B]
    rank: jax.Array  # int32[B]
    leader: jax.Array  # int32[B]
    stuck: jax.Array  # bool[B] — not resolved and not missing-blocked:
    # cycles the device pass could not collapse; host oracle finishes them.


@functools.partial(jax.jit, static_argnames=("max_iters",))
def resolve_general(
    deps: jax.Array,  # int32[B, D]
    dot_src: jax.Array,
    dot_seq: jax.Array,
    *,
    max_iters: int = 0,  # 0 -> auto: 4 * log2(B) + 8
) -> GeneralResolution:
    """Batched resolution for out-degree-D graphs.

    Affine-max pointer doubling: each dependency slot of vertex v is a
    constraint ``rank[v] >= max(floor, add + rank[target])``.  A slot whose
    target has finalized folds into the floor; a slot whose target has
    exactly one live slot composes through it (chain doubling); any live
    target always contributes its current floor (monotone relaxation), so
    progress never stalls on merge vertices — worst case degrades to
    frontier peeling, typical per-key-chain graphs finish in O(log depth).

    SCCs whose vertices are connected by *mutual* edges (the dominant
    shape: k concurrent conflicting proposals that all saw each other,
    k = 2 being two replicas racing) are collapsed exactly by a
    mutual-edge connected-components pre-pass.  Cycles with no mutual
    edges (delivery orders where conflict visibility is strictly
    one-directional around a ring) surface as ``stuck`` for the host
    Tarjan oracle to finish — they cannot deadlock or spin the device
    pass: floors/adds saturate at the batch size, after which the loop
    settles and the budget check exits early.
    """
    batch, width = deps.shape
    idx = jnp.arange(batch, dtype=jnp.int32)
    if max_iters == 0:
        max_iters = 4 * _num_doubling_steps(batch) + 8

    # self-dependencies are semantic no-ops (a command never waits on
    # itself); prune them up front like the host oracle (tarjan.py:129) —
    # left in, they'd read as unfinishable frozen slots in the iterative
    # pass and falsely disqualify the backward fast path
    deps = jnp.where(deps == idx[:, None], TERMINAL, deps)

    # --- fast path: every dependency points backward in batch order and
    # nothing is missing.  This is the dominant executor shape (deps are
    # latest-per-key at commit time, appended in commit order), and it
    # makes batch order itself a topological order: backward-only edges
    # cannot form cycles, so every SCC is a singleton and emitting in
    # arrival order satisfies the per-key dependency contract.  The
    # iterative machinery below costs O(critical-path alternations) rounds
    # of B-wide gathers — measured 6.7 s at B=262k, D=4 on deep chains —
    # while this check is one elementwise pass.
    backward_only = jnp.where(deps >= 0, deps < idx[:, None], True).all()
    fast = backward_only & ~(deps == MISSING).any()

    def _fast_arrival():
        ones = jnp.ones((batch,), bool)
        return idx, ones, idx, idx, jnp.zeros((batch,), bool)

    def _iterative():
        return _resolve_general_iterative(deps, dot_src, dot_seq, max_iters)

    return GeneralResolution(*jax.lax.cond(fast, _fast_arrival, _iterative))


@functools.partial(jax.jit, static_argnames=("run_to_fixpoint",))
def _peel_stage(tgt, floor, miss, final, rank, *, run_to_fixpoint: bool):
    """One stage of the staged peeler: frontier peeling (absorption only,
    one dependency level per round) until progress stops or — unless
    ``run_to_fixpoint`` — the live set halves, at which point the caller
    compacts and re-dispatches at half size, so total work tracks the
    frontier-size integral (sum of per-level live counts), not B x depth."""
    half = jnp.int32(max(tgt.shape[0] // 2, 1))

    def body(state):
        tgt, floor, miss, final, rank, _changed = state
        live = tgt >= 0
        safe = jnp.where(live, tgt, 0)
        t_final = final[safe]
        t_miss = miss[safe]
        fold = live & t_final
        new_floor = jnp.maximum(
            floor, jnp.where(fold, rank[safe] + 1, 0).max(axis=-1)
        )
        new_tgt = jnp.where(fold, jnp.int32(TERMINAL), tgt)
        new_miss = miss | (live & t_miss).any(axis=-1)
        open_slots = (new_tgt >= 0).sum(axis=-1)
        newly_final = ~final & ~new_miss & (open_slots == 0)
        new_rank = jnp.where(newly_final, new_floor, rank)
        new_final = final | newly_final
        changed = newly_final.any() | (new_miss != miss).any()
        return new_tgt, new_floor, new_miss, new_final, new_rank, changed

    def cond(state):
        _tgt, _floor, miss, final, _rank, changed = state
        if run_to_fixpoint:
            return changed
        return changed & ((~final & ~miss).sum() > half)

    state = (tgt, floor, miss, final, rank, jnp.bool_(True))
    tgt, floor, miss, final, rank, changed = jax.lax.while_loop(
        cond, body, state
    )
    return tgt, floor, miss, final, rank, changed


def resolve_general_staged(
    deps,  # int32[B, W] numpy or jax — TERMINAL/MISSING sentinels
    dot_src,
    dot_seq,
    *,
    min_size: int = 4096,
) -> GeneralResolution:
    """Exact DAG resolution with frontier-size-proportional cost.

    The in-jit ``resolve_general`` budget pays O(B x W) per round for a
    fixed ~4 log B rounds — deep alternating-chain graphs (measured
    critical path 2187 at 262k x 4) blow through it with most rows
    unresolved (VERDICT r3 weak #3).  This host-orchestrated variant peels
    dependency levels with a jitted while_loop per *stage*, compacting the
    live rows to half capacity between stages: each level's cost is the
    current live count, so the total is the frontier-size integral
    (sum over vertices of their depth terms), at ~log(B / min_size) extra
    compiles + host syncs.

    Cycles never peel: they survive every stage and return as ``stuck``
    (leader = self; the host Tarjan oracle finishes them, as with
    ``resolve_general``).  Missing-blocked rows and their dependents come
    back unresolved and not stuck.

    The stage kernel always runs on the host CPU backend, even when the
    process default is an accelerator: this variant is host-orchestrated
    (numpy compaction between stages) and its per-level work is a few
    tiny gathers over the live set — accelerator dispatch buys nothing,
    while on a remote-dispatch rig the fixpoint's per-level kernel chain
    is catastrophic (measured 923 ms at 32k x 4 over the TPU tunnel vs
    127 ms CPU-pinned in the same process; the co-located CPU child does
    the same work in ~12 ms).  The in-dispatch resolvers
    (``resolve_general``, ``resolve_keyed_auto``) remain the accelerator
    hot path."""
    import numpy as np

    try:
        _stage_dev = jax.local_devices(backend="cpu")[0]
    except RuntimeError:  # no cpu backend registered: keep the default
        _stage_dev = None

    def _stage_ctx():
        if _stage_dev is not None:
            return jax.default_device(_stage_dev)
        return contextlib.nullcontext()

    deps = np.asarray(deps, dtype=np.int32)
    batch, width = deps.shape
    idx32 = np.arange(batch, dtype=np.int32)
    # self-deps are semantic no-ops (tarjan.py:129)
    deps = np.where(deps == idx32[:, None], TERMINAL, deps)

    # stage-local state starts as the full batch; rows with a MISSING
    # sentinel are missing-blocked from the outset (and their dependents
    # catch it through propagation in the peel rounds)
    orig = idx32.copy()  # stage row -> original row
    tgt = deps.copy()
    floor = np.zeros(batch, np.int32)
    miss = (deps == MISSING).any(axis=1)
    final = np.zeros(batch, bool)
    rank_local = np.zeros(batch, np.int32)

    # full-batch outputs, filled in as rows finalize
    out_rank = np.full(batch, _UNRESOLVED_RANK, np.int32)
    out_final = np.zeros(batch, bool)
    out_miss = np.zeros(batch, bool)

    prev_live = None
    while True:
        size = _pow2_at_least(max(len(orig), 1))
        pad = size - len(orig)
        if pad:
            tgt = np.concatenate(
                [tgt, np.full((pad, width), TERMINAL, np.int32)]
            )
            floor = np.concatenate([floor, np.zeros(pad, np.int32)])
            miss = np.concatenate([miss, np.zeros(pad, bool)])
            final = np.concatenate([final, np.ones(pad, bool)])  # inert
            rank_local = np.concatenate([rank_local, np.zeros(pad, np.int32)])
        with _stage_ctx():
            j_out = _peel_stage(
                jnp.asarray(tgt), jnp.asarray(floor), jnp.asarray(miss),
                jnp.asarray(final), jnp.asarray(rank_local),
                run_to_fixpoint=size <= min_size,
            )
        # one blocking transfer for the stage's whole output (device_get
        # issues async copies for every leaf before blocking) — per-array
        # np.asarray would pay one device round trip *each*, which on a
        # remote-tunnel rig multiplies the stage cost by ~5
        tgt, floor, miss, final, rank_local = jax.device_get(j_out[:5])
        tgt, floor, miss, final, rank_local = (
            tgt[: len(orig)], floor[: len(orig)], miss[: len(orig)],
            final[: len(orig)], rank_local[: len(orig)],
        )

        # publish finalized / missing rows
        out_final[orig[final]] = True
        out_rank[orig[final]] = rank_local[final]
        out_miss[orig[miss]] = True

        live = ~final & ~miss
        n_live = int(live.sum())
        if n_live == 0 or size <= min_size:
            # done, or the terminal stage ran to its fixpoint: any
            # survivor is cycle-blocked and returns as stuck
            break
        if prev_live is not None and n_live >= prev_live:
            # a larger-than-terminal stage hit a fixpoint with no progress:
            # everything left is cycle-blocked — stop instead of spinning
            break
        prev_live = n_live

        # compact to the live rows; fold deps on finalized/missing rows
        keep = np.nonzero(live)[0].astype(np.int32)
        remap = np.full(len(orig), TERMINAL, np.int32)
        remap[keep] = np.arange(len(keep), dtype=np.int32)
        new_tgt = tgt[keep]
        valid = new_tgt >= 0
        t_rows = np.where(valid, new_tgt, 0)
        t_final = final[t_rows] & valid
        t_miss = miss[t_rows] & valid
        new_floor = np.maximum(
            floor[keep],
            np.where(t_final, rank_local[t_rows] + 1, 0).max(axis=1),
        )
        new_miss = t_miss.any(axis=1)
        folded = np.where(
            valid & t_final, TERMINAL, np.where(valid, remap[t_rows], new_tgt)
        )
        orig = orig[keep]
        tgt = folded.astype(np.int32)
        floor = new_floor.astype(np.int32)
        miss = new_miss
        final = np.zeros(len(orig), bool)
        rank_local = np.zeros(len(orig), np.int32)

    stuck_np = ~out_final & ~out_miss
    order = np.lexsort(
        (
            np.asarray(dot_seq),
            np.asarray(dot_src),
            idx32,
            np.where(out_final, out_rank, _UNRESOLVED_RANK),
        )
    ).astype(np.int32)
    # host numpy, deliberately: this variant is host-orchestrated and its
    # consumers read the results on host — bouncing them through the device
    # would cost an upload plus a fetch round trip per field
    return GeneralResolution(
        order,
        out_final,
        np.where(out_final, out_rank, _UNRESOLVED_RANK),
        idx32,
        stuck_np,
    )


# slot sentinel internal to resolve_general_resident's compaction: a dep
# whose target was cut at a fixpoint compaction (permanently stuck live
# rows past the stage capacity) — only ever created after the publish
# gate closed, so it is never read into a published result
_FROZEN = -3


def _resident_schedule(batch: int, min_size: int) -> Tuple[int, ...]:
    """Static pow2 halving schedule from the padded batch down to the
    terminal stage size (inclusive)."""
    sizes = []
    size = _pow2_at_least(max(batch, 1))
    floor_size = _pow2_at_least(max(min_size, 1))
    while size > floor_size:
        sizes.append(size)
        size //= 2
    sizes.append(size)
    return tuple(sizes)


@functools.partial(jax.jit, static_argnames=("min_size",))
def resolve_general_resident(
    deps: jax.Array,  # int32[B, W] — TERMINAL/MISSING sentinels
    dot_src: jax.Array,
    dot_seq: jax.Array,
    *,
    min_size: int = 4096,
) -> GeneralResolution:
    """``resolve_general_staged`` with the state kept DEVICE-RESIDENT
    between stages: the whole peel-and-compact schedule — frontier
    peeling until the live set halves, device-side compaction to half
    capacity, repeat down to ``min_size``, terminal fixpoint — runs as
    ONE jitted dispatch with no host round-trips.

    The host-orchestrated variant pays a full state fetch + re-upload
    per stage (the reason its stage kernel is CPU-pinned: measured
    923 ms at 32k x 4 over the TPU dispatch tunnel); this one costs a
    single dispatch + one result fetch, so the adversarial fallback
    (``bench.py general_fallback_*``) is slope-timeable and serves from
    the accelerator like every other in-dispatch resolver — closing the
    ~300x general-path fallback cliff (ROADMAP item 4).

    Semantics are the staged peeler's exactly (parity-tested): DAG rows
    finalize with frontier-proportional total cost, missing-blocked rows
    and their dependents come back unresolved-not-stuck, cycles never
    peel and return ``stuck`` for the host Tarjan oracle.  The one
    divergence-shaped corner — a fixpoint reached while the live set
    still exceeds the next stage's capacity — closes the publish gate:
    results are already final at a fixpoint, so later stages (whose cut
    rows would dangle) cannot corrupt them.
    """
    batch, width = deps.shape
    idx = jnp.arange(batch, dtype=jnp.int32)
    # self-deps are semantic no-ops (tarjan.py:129)
    deps = jnp.where(deps == idx[:, None], TERMINAL, deps)

    # full-batch outputs, scatter-published as stages finalize rows
    out_final = jnp.zeros((batch,), bool)
    out_miss = jnp.zeros((batch,), bool)
    out_rank = jnp.full((batch,), _UNRESOLVED_RANK, jnp.int32)

    schedule = _resident_schedule(batch, min_size)
    size0 = schedule[0]
    pad = size0 - batch
    iota0 = jnp.arange(size0, dtype=jnp.int32)
    tgt = jnp.full((size0, width), TERMINAL, jnp.int32).at[:batch].set(deps)
    floor = jnp.zeros((size0,), jnp.int32)
    miss = jnp.zeros((size0,), bool).at[:batch].set((deps == MISSING).any(axis=1))
    final = iota0 >= batch  # pads are inert
    rank = jnp.zeros((size0,), jnp.int32)
    orig = jnp.where(iota0 < batch, iota0, jnp.int32(batch))  # pad -> dropped

    dead = jnp.bool_(False)  # publish gate (see docstring)
    for size in schedule:
        tgt, floor, miss, final, rank, _changed = _peel_stage(
            tgt, floor, miss, final, rank,
            run_to_fixpoint=size <= min_size,
        )
        pub_final = out_final.at[orig].set(final, mode="drop")
        pub_miss = out_miss.at[orig].set(miss, mode="drop")
        pub_rank = out_rank.at[orig].set(
            jnp.where(final, rank, _UNRESOLVED_RANK), mode="drop"
        )
        out_final = jnp.where(dead, out_final, pub_final)
        out_miss = jnp.where(dead, out_miss, pub_miss)
        out_rank = jnp.where(dead, out_rank, pub_rank)
        if size <= min_size:
            break  # terminal stage ran to its fixpoint

        # --- device-side compaction to half capacity ---
        half = size // 2
        live = ~final & ~miss
        # a fixpoint with live > half means every survivor is
        # permanently blocked: results above are final — close the gate
        # (cut rows may dangle below, but nothing publishes past here)
        dead = dead | (live.sum() > half)
        iota = jnp.arange(size, dtype=jnp.int32)
        _, perm = jax.lax.sort(
            ((~live).astype(jnp.int32), iota), num_keys=1, is_stable=True
        )
        keep = perm[:half]
        remap = (
            jnp.full((size,), _FROZEN, jnp.int32)
            .at[keep]
            .set(jnp.arange(half, dtype=jnp.int32))
        )
        tgt_k = tgt[keep]
        valid = tgt_k >= 0
        t_rows = jnp.where(valid, tgt_k, 0)
        t_final = final[t_rows] & valid
        t_miss = miss[t_rows] & valid
        floor = jnp.maximum(
            floor[keep],
            jnp.where(t_final, rank[t_rows] + 1, 0).max(axis=1),
        )
        miss = miss[keep] | t_miss.any(axis=1)
        tgt = jnp.where(
            t_final, jnp.int32(TERMINAL), jnp.where(valid, remap[t_rows], tgt_k)
        )
        final = final[keep]
        rank = rank[keep]
        orig = orig[keep]

    stuck = ~out_final & ~out_miss
    order = jnp.lexsort(
        (
            dot_seq,
            dot_src,
            idx,
            jnp.where(out_final, out_rank, _UNRESOLVED_RANK),
        )
    ).astype(jnp.int32)
    return GeneralResolution(order, out_final, out_rank, idx, stuck)


# ---------------------------------------------------------------------------
# resident graph-plane step (executor/graph/graph_plane.DeviceGraphPlane)
# ---------------------------------------------------------------------------


class GraphPlaneStep(NamedTuple):
    """One resident dispatch's output: the donated backlog state back,
    plus the emitted order.  Only the small per-slot result columns
    (order/newly/stuck/leader) are fetched by the host — the backlog
    state itself never round-trips."""

    deps: jax.Array  # int32[C, W] — resident dep-slot matrix (donated)
    key: jax.Array  # int32[C] conflict-key hash (-1 = multi-key)
    src: jax.Array  # int32[C]
    seq: jax.Array  # int32[C]
    occ: jax.Array  # bool[C] — slot holds a committed command
    executed: jax.Array  # bool[C]
    order: jax.Array  # int32[C] permutation; emitted = order rows w/ newly
    newly: jax.Array  # bool[C] — executed by this dispatch
    stuck: jax.Array  # bool[C] — general modes: cycles for the host oracle
    leader: jax.Array  # int32[C] — structure modes: SCC leader (CHAIN_SIZE)


def graph_plane_step_core(
    deps: jax.Array,  # int32[C, W] slot indices / TERMINAL / MISSING
    key: jax.Array,  # int32[C]
    src: jax.Array,  # int32[C]
    seq: jax.Array,  # int32[C]
    occ: jax.Array,  # bool[C]
    executed: jax.Array,  # bool[C]
    u_row: jax.Array,  # int32[U] — new slot ids (pad = C, dropped)
    u_deps: jax.Array,  # int32[U, W]
    u_key: jax.Array,  # int32[U]
    u_src: jax.Array,  # int32[U]
    u_seq: jax.Array,  # int32[U]
    p_row: jax.Array,  # int32[P] — dep-patch cells (pad = C, dropped)
    p_col: jax.Array,  # int32[P]
    p_val: jax.Array,  # int32[P] — slot id or TERMINAL
    e_row: jax.Array,  # int32[E] — host-oracle executed marks (pad = C)
    *,
    mode: str,  # "keyed" | "general" | "general_resident"
) -> GraphPlaneStep:
    """The resident twin of ``BatchedDependencyGraph._resolve_backlog``
    (executor/graph/graph_plane.py).

    The whole dependency backlog lives ON DEVICE across feeds: ``C``
    slots of (deps, key, src, seq) with occupancy and executed flags,
    all donated in-place.  Each dispatch (1) installs the feed's new
    rows, (2) re-points MISSING dep cells whose dot just committed (the
    waiter-index residual protocol: missing-blocked rows stay resident
    and wake when a later feed patches them), (3) applies host-oracle
    executed marks (stuck-cycle residues the host Tarjan finished), then
    (4) resolves the *entire* pending window with the same kernels the
    host-column path dispatches per flush — ``resolve_keyed_auto``'s
    sort-based kernel for single-key functional windows,
    ``resolve_general`` (small, exact structure) or
    ``resolve_general_resident`` (large, peel-and-compact) otherwise —
    folding dep cells that point at executed slots to TERMINAL first.

    Non-pending slots (free, or executed-but-not-yet-compacted) are
    masked inert: private pad keys + TERMINAL deps make them resolve as
    singleton runs, and the host drops them via ``newly``.  Slot
    recycling is host-owned (compaction re-packs pending rows and
    re-uploads once).
    """
    cap, _width = deps.shape
    idx = jnp.arange(cap, dtype=jnp.int32)

    # (1) new rows: full-row install (reused slots fully overwritten)
    deps = deps.at[u_row].set(u_deps, mode="drop")
    key = key.at[u_row].set(u_key, mode="drop")
    src = src.at[u_row].set(u_src, mode="drop")
    seq = seq.at[u_row].set(u_seq, mode="drop")
    occ = occ.at[u_row].set(True, mode="drop")
    executed = executed.at[u_row].set(False, mode="drop")
    # (2) dep patches: MISSING cells whose dot just committed (or was
    # recovered as a noop -> TERMINAL)
    deps = deps.at[p_row, p_col].set(p_val, mode="drop")
    # (3) host-oracle executed marks (stuck residues finished on host)
    executed = executed.at[e_row].set(True, mode="drop")

    pending = occ & ~executed
    cell_live = deps >= 0
    safe = jnp.clip(deps, 0, cap - 1)
    # fold deps on executed slots to TERMINAL; mask non-pending rows inert
    dmat = jnp.where(cell_live & executed[safe], jnp.int32(TERMINAL), deps)
    dmat = jnp.where(pending[:, None], dmat, jnp.int32(TERMINAL))

    zeros_i = jnp.zeros((cap,), jnp.int32)
    if mode == "keyed":
        # single-dep column: the first live cell, else MISSING if any cell
        # is missing, else TERMINAL (the host-column path's compression)
        live = dmat >= 0
        has_live = live.any(axis=1)
        first = jnp.argmax(live, axis=1)
        col = jnp.take_along_axis(dmat, first[:, None], axis=1)[:, 0]
        col = jnp.where(
            has_live,
            col,
            jnp.where((dmat == MISSING).any(axis=1), MISSING, TERMINAL),
        ).astype(jnp.int32)
        # distinct private keys park every non-pending slot in its own
        # singleton run (one shared key would flood the residual)
        pk = jnp.where(pending, key, jnp.iinfo(jnp.int32).max - idx)
        # a SMALL residual, deliberately: the plane's window is mostly
        # chain-verified rows plus a thin blocked residue, and the
        # residual finish (doubling + closure scatters) is the dispatch's
        # dominant cost when sized to the window; overflow falls back to
        # exact full-window doubling in-dispatch.  No structure entry:
        # the plane reports aggregate counters, not exact CHAIN_SIZE
        # (the host-column twin keeps the exact-structure path)
        residual_size = _pow2_at_least(max(64, cap // 16))
        res = resolve_functional_keyed(
            pk, col, src, seq,
            residual_size=min(residual_size, cap),
            return_structure=False,
        )

        def _kept():
            # per-vertex resolved from the order permutation (resolved
            # rows sort first): position-in-order < n_resolved
            pos = zeros_i.at[res.order].set(idx)
            return res.order, pos < res.n_resolved

        if residual_size >= cap:
            order, resolved_v = _kept()
        else:

            def _overflowed():
                # residual overflow: rerun via exact full-window doubling
                # (the resolve_keyed_auto fallback, in-dispatch)
                full = resolve_functional(col, src, seq)
                return full.order, full.resolved

            order, resolved_v = jax.lax.cond(res.overflow, _overflowed, _kept)
        stuck = jnp.zeros((cap,), bool)  # functional cycles resolve exactly
        leader = zeros_i
    elif mode == "general":
        res = resolve_general(dmat, src, seq)
        order, resolved_v = res.order, res.resolved
        stuck = res.stuck & pending
        leader = res.leader
    else:
        assert mode == "general_resident", mode
        res = resolve_general_resident(dmat, src, seq)
        order, resolved_v = res.order, res.resolved
        stuck = res.stuck & pending
        leader = res.leader

    newly = resolved_v & pending
    executed = executed | newly
    return GraphPlaneStep(
        deps, key, src, seq, occ, executed, order, newly, stuck, leader
    )


# the composed program: graph_plane_step_core compiled as one donated
# dispatch (the pre-Pallas default, and the fallback route).  The core
# stays un-jitted so the Pallas kernel (ops/pallas_resolve.py) can trace
# the IDENTICAL program inside its kernel body — parity by construction.
resolve_graph_plane_step_xla = functools.partial(
    jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5), static_argnames=("mode",)
)(graph_plane_step_core)

register_program("graph_plane_step_xla", resolve_graph_plane_step_xla)


def resolve_graph_plane_step(
    deps, key, src, seq, occ, executed,
    u_row, u_deps, u_key, u_src, u_seq,
    p_row, p_col, p_val, e_row,
    *,
    mode: str,
) -> GraphPlaneStep:
    """Route one resident graph-plane dispatch: the Pallas-fused kernel
    when :func:`fantoch_tpu.ops.pallas_resolve.pallas_enabled` says so
    (and the backlog fits VMEM), else the composed
    :func:`resolve_graph_plane_step_xla`.  Same signature, donation set,
    and bit-for-bit :class:`GraphPlaneStep` either way — executors, twin
    replay, and shadow checks all call through here."""
    from fantoch_tpu.ops import pallas_resolve as pr

    args = (deps, key, src, seq, occ, executed,
            u_row, u_deps, u_key, u_src, u_seq, p_row, p_col, p_val, e_row)
    if pr.pallas_enabled() and pr._fits_vmem(deps, key, src, seq, u_deps):
        return pr.route_dispatch(
            "graph_plane_step", pr.graph_plane_step_pallas,
            resolve_graph_plane_step_xla, args, {"mode": mode},
        )
    return resolve_graph_plane_step_xla(*args, mode=mode)


def _resolve_general_iterative(deps, dot_src, dot_seq, max_iters):
    """The exact fallback: mutual-edge SCC collapse + affine-max doubling
    (see resolve_general).  Returns the GeneralResolution fields."""
    batch, width = deps.shape
    idx = jnp.arange(batch, dtype=jnp.int32)

    # --- mutual-edge SCC collapse: v and u mutually dependent -> same SCC,
    # and so is the whole connected component of the (undirected) mutual-
    # edge graph.  leader = min id of the component, found by min-label
    # propagation over mutual neighbours with pointer jumping; intra-
    # component edges are pruned and inbound edges retargeted.
    tgt = deps  # int32[B, D]
    valid = tgt >= 0
    safe_tgt = jnp.where(valid, tgt, 0)
    # reverse test: does any slot of target point back at v?
    back = (tgt[safe_tgt] == idx[:, None, None]).any(axis=-1) & valid
    leader = idx
    for _ in range(_num_doubling_steps(batch)):
        # min over mutual neighbours' leaders, then pointer jump
        nbr_min = jnp.where(back, leader[safe_tgt], jnp.int32(batch)).min(axis=-1)
        leader = jnp.minimum(leader, nbr_min)
        leader = jnp.minimum(leader, leader[leader])

    # rewrite deps through leaders; drop intra-SCC edges
    tgt = jnp.where(valid, leader[safe_tgt], tgt)
    tgt = jnp.where(valid & (tgt == leader[:, None]), TERMINAL, tgt)
    # non-leaders hand their external deps to... they keep them: every
    # member's constraints apply to the SCC; members share the leader's
    # rank at the end, so fold member floors via a segment-max on leader.

    is_miss = tgt == MISSING
    add = jnp.where(tgt >= 0, 1, 0).astype(jnp.int32)
    floor = jnp.zeros((batch, width), dtype=jnp.int32)
    missing_blocked = is_miss.any(axis=-1)

    member_count = jnp.zeros(batch, jnp.int32).at[leader].add(1)

    def body(state):
        it, tgt, add, floor, missing_blocked, _changed = state
        # a slot that composed all the way around a 3+-cycle points at its
        # own vertex: frozen — excluded from folding, absorption and
        # composition so the loop settles and the budget exits early; the
        # vertex stays live and surfaces as ``stuck``.
        frozen = tgt == idx[:, None]
        live = (tgt >= 0) & ~frozen
        safe = jnp.where(live, tgt, 0)
        n_live = live.sum(axis=-1)  # live slots per vertex row
        vfloor = floor.max(axis=-1)  # row lower bound

        # SCC-aggregate view (live targets are always leaders): a slot on a
        # multi-member SCC must fold the *aggregate* rank and wait for all
        # members, or dependents would undercut 1 + scc_rank.
        agg_floor = jnp.zeros(batch, jnp.int32).at[leader].max(vfloor)
        agg_live = jnp.zeros(batch, jnp.int32).at[leader].add(n_live)
        agg_miss = jnp.zeros(batch, bool).at[leader].max(missing_blocked)
        agg_frozen = jnp.zeros(batch, bool).at[leader].max(frozen.any(axis=-1))
        agg_final = (agg_live == 0) & ~agg_miss & ~agg_frozen

        t_final = agg_final[safe]
        t_miss = agg_miss[safe]
        t_vfloor = agg_floor[safe]

        # (a) finalized target SCC: fold into floor, close the slot
        new_floor = jnp.where(live & t_final, jnp.maximum(floor, add + t_vfloor), floor)
        new_tgt = jnp.where(live & t_final, TERMINAL, tgt)
        new_add = add

        # (b) missing-blocked target: vertex becomes missing-blocked
        new_missing = missing_blocked | (live & t_miss).any(axis=-1)

        # (c) live target: always absorb its floor (relaxation)...
        still = live & ~t_final & ~t_miss
        new_floor = jnp.where(still, jnp.maximum(new_floor, add + t_vfloor), new_floor)
        # ...and compose through singleton-SCC targets with one live slot
        # (chain doubling); stop composing once ``add`` saturates — a legit
        # chain has < batch hops, so only unwrapped cycles ever get there.
        single = (
            still
            & (agg_live[safe] == 1)
            & (member_count[safe] == 1)
            & (add < jnp.int32(batch))
        )
        # compose through the target's single live slot.  Precompute each
        # vertex's (first-live-slot target, add) as [B] columns so the
        # per-slot lookup is a [B, D] gather — the naive formulation
        # ``((tgt >= 0) & ~frozen)[safe]`` materializes [B, D, D]
        # (VERDICT r2 weak #7: 256M elements per iteration at B=1M, D=16).
        live_slot = jnp.argmax(live, axis=-1)[..., None]  # [B, 1]
        comp_tgt = jnp.take_along_axis(tgt, live_slot, axis=-1)[..., 0]  # [B]
        comp_add = jnp.take_along_axis(add, live_slot, axis=-1)[..., 0]  # [B]
        new_tgt = jnp.where(single, comp_tgt[safe], new_tgt)
        new_add = jnp.where(single, add + comp_add[safe], new_add)
        # a composition that lands on the vertex itself wrapped a cycle the
        # mutual-edge pass missed; it becomes ``frozen`` next iteration

        # saturate: legitimate ranks/hop-counts are < batch, so capping at
        # batch only affects un-collapsible cycles — whose floors would
        # otherwise grow (and overflow) forever, keeping ``changed`` true
        # for the whole budget instead of settling in O(log batch) rounds.
        new_floor = jnp.minimum(new_floor, jnp.int32(batch))
        new_add = jnp.minimum(new_add, jnp.int32(batch))

        changed = (
            (new_tgt != tgt).any() | (new_floor != floor).any() | (new_missing != missing_blocked).any()
        )
        return it + 1, new_tgt, new_add, new_floor, new_missing, changed

    def cond(state):
        it, _tgt, _add, _floor, _miss, changed = state
        return (it < max_iters) & changed

    state = (jnp.int32(0), tgt, add, floor, missing_blocked, jnp.bool_(True))
    _, tgt, add, floor, missing_blocked, _ = jax.lax.while_loop(cond, body, state)

    live = tgt >= 0
    final = (live.sum(axis=-1) == 0) & ~missing_blocked
    vrank = floor.max(axis=-1)

    # fold SCC members onto their leader: shared rank = max member rank
    scc_rank = jnp.zeros(batch, jnp.int32).at[leader].max(jnp.where(final, vrank, 0))
    scc_final = jnp.ones(batch, bool).at[leader].min(final)
    scc_missing = jnp.zeros(batch, bool).at[leader].max(missing_blocked)
    resolved = scc_final[leader] & ~scc_missing[leader]
    rank = jnp.where(resolved, scc_rank[leader], _UNRESOLVED_RANK).astype(jnp.int32)
    stuck = ~resolved & ~(missing_blocked | scc_missing[leader])

    order = _order_from_ranks(rank, leader, dot_src, dot_seq)
    return order, resolved, rank, leader, stuck
