"""Batched device kernels for the Newt/Tempo timestamp path.

The two hot loops of the table protocol/executor become array ops:

* ``batched_clock_proposal`` — the tensor twin of
  ``SequentialKeyClocks::proposal`` (fantoch_ps/src/protocol/common/table/
  clocks/keys/sequential.rs:36-47) for a batch of single-key commands:
  commands on the same key receive consecutive clocks continuing from the
  key's prior clock, each lower-bounded by its ``min_clock``.  Within one
  key group ordered j = 0..m-1::

      clock_j = max(min_j, clock_{j-1} + 1)
              = rank_j + max_{i <= j}(max(prior+1, min_i) - rank_i)

  a segmented max-scan of ``max(prior+1, min) - rank`` — one sort, one
  cummax, one scatter.  Vote ranges are born compressed: process p votes
  ``(prev_end + 1, clock_j)`` per command (votes.rs try_compress shapes).

* ``stable_clocks`` — the tensor twin of ``VotesTable::stable_clock``
  (fantoch_ps/src/executor/table/mod.rs:247-270) over all key tables at
  once: sort the per-process vote frontiers along the process axis and take
  the ``(n - threshold)``-th column.

Both are shape-static, fully jittable, and batch-friendly: one kernel
launch replaces B hash-map bumps / K BTree walks.

Clock width: device clocks are **31-bit windowed**.  Raw wall-clock micros
(Newt's real-time mode) overflow int32 after ~35 minutes, so callers must
rebase device clocks against a window floor before the kernel — the
natural floor is the GC'd stable clock the protocol already tracks, and
timestamps are only ever compared within a window (votes below the stable
floor are collected; proposals are bounded by floor + in-flight commands).
The host twins (table_clocks.py) use unbounded Python ints and need no
rebasing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_WINDOW_MAX = (1 << 31) - 1


class ClockWindow:
    """31-bit device-clock window over unbounded host clocks.

    Owns the rebasing the module docstring demands of callers: host-side
    clocks are int64 (Newt's real-time mode uses wall-clock micros, which
    overflow int32 after ~35 minutes); device kernels see
    ``clock - floor`` as int32.  The floor advances monotonically with the
    protocol's GC'd stable clock — every *live* comparison happens above
    it, so subtracting it is order-preserving.

    ``advance`` returns the shift to apply to device-resident clock tables
    (see :func:`shift_table`); entries at or below the new floor clamp to
    0, which keeps proposal semantics (``max(prior + 1, min)``) because a
    floor-or-older prior constrains nothing above the floor.
    """

    __slots__ = ("_floor",)

    def __init__(self, floor: int = 0):
        assert floor >= 0
        self._floor = int(floor)

    @property
    def floor(self) -> int:
        return self._floor

    def rebase(self, values) -> np.ndarray:
        """Host int64 clocks -> int32 device clocks (values - floor).

        Zero stays zero (the \"no clock yet\" bottom), everything else must
        lie in (floor, floor + 2^31)."""
        values = np.asarray(values, dtype=np.int64)
        out = np.where(values == 0, 0, values - self._floor)
        # strict: a clock exactly at the floor would alias the bottom (0)
        assert (out[values != 0] > 0).all(), (
            f"clock at or below the window floor {self._floor}: "
            f"min {values.min()}"
        )
        assert (out <= _WINDOW_MAX).all(), (
            f"clock overflows the 31-bit window above floor {self._floor}: "
            f"max {values.max()} (advance the window)"
        )
        return out.astype(np.int32)

    def restore(self, device_values) -> np.ndarray:
        """Device int32 clocks -> host int64 clocks (values + floor)."""
        vals = np.asarray(device_values, dtype=np.int64)
        return np.where(vals == 0, 0, vals + self._floor)

    def advance(self, new_floor: int) -> int:
        """Move the floor forward (monotone); returns the int32 shift to
        subtract from device-resident clock tables."""
        new_floor = int(new_floor)
        assert new_floor >= self._floor, "window floor is monotone"
        shift = new_floor - self._floor
        assert shift <= _WINDOW_MAX
        self._floor = new_floor
        return shift


@jax.jit
def shift_table(table: jax.Array, shift) -> jax.Array:
    """Rebase a device-resident clock table after ``ClockWindow.advance``:
    entries at or below the new floor clamp to 0 (no constraint)."""
    return jnp.maximum(table - jnp.int32(shift), 0)


@jax.jit
def batched_clock_proposal(
    prior: jax.Array,  # int32[K] — key clock before the batch
    key: jax.Array,  # int32[B] — key bucket per command
    min_clock: jax.Array,  # int32[B] — proposal lower bound (0 if none)
):
    """Returns ``(clock[B], vote_start[B], new_prior[K])``.

    ``clock`` is the proposed timestamp per command; the voter's consumed
    range for command i is ``(vote_start[i], clock[i])``; ``new_prior`` is
    the key-clock table after the whole batch (== the last clock per key).
    Batch order is proposal order within each key (the worker's arrival
    order, as in the sequential reference).
    """
    batch = key.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)

    # group commands by key, preserving batch order inside groups
    perm = jnp.argsort(key, stable=True).astype(jnp.int32)
    k_sorted = key[perm]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), k_sorted[1:] != k_sorted[:-1]]
    )
    # rank within the key group
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    group_first = jnp.where(seg_start, idx, 0)
    group_first = jax.lax.associative_scan(jnp.maximum, group_first)
    rank = idx - group_first

    base = jnp.maximum(prior[k_sorted] + 1, min_clock[perm])  # max(prior+1, min)
    # segmented running max of (base - rank), resetting at segment starts:
    # scan (seg_id, value) pairs where the combiner keeps the right operand's
    # value unless both sides share a segment — associative, no magic
    # offsets, no overflow for any clock magnitude.
    def seg_max(a, b):
        a_seg, a_val = a
        b_seg, b_val = b
        return b_seg, jnp.where(a_seg == b_seg, jnp.maximum(a_val, b_val), b_val)

    _, running = jax.lax.associative_scan(seg_max, (seg_id, base - rank))
    clock_sorted = rank + running

    clock = jnp.zeros((batch,), jnp.int32).at[perm].set(clock_sorted)
    # voter's range start: previous clock on this key + 1
    prev_clock_sorted = jnp.where(
        seg_start, prior[k_sorted], jnp.roll(clock_sorted, 1)
    )
    vote_start = jnp.zeros((batch,), jnp.int32).at[perm].set(prev_clock_sorted + 1)

    new_prior = prior.at[key].max(clock)
    return clock, vote_start, new_prior


@functools.partial(jax.jit, static_argnames=("threshold",))
def stable_clocks(frontiers: jax.Array, *, threshold: int) -> jax.Array:
    """Stable clock per key: the ``(n - threshold)``-th smallest of the n
    per-process vote frontiers (``int32[K, n] -> int32[K]``)."""
    n = frontiers.shape[1]
    assert threshold <= n
    return jnp.sort(frontiers, axis=1)[:, n - threshold]
