"""Batched device kernels for the Newt/Tempo timestamp path.

The two hot loops of the table protocol/executor become array ops:

* ``batched_clock_proposal`` — the tensor twin of
  ``SequentialKeyClocks::proposal`` (fantoch_ps/src/protocol/common/table/
  clocks/keys/sequential.rs:36-47) for a batch of single-key commands:
  commands on the same key receive consecutive clocks continuing from the
  key's prior clock, each lower-bounded by its ``min_clock``.  Within one
  key group ordered j = 0..m-1::

      clock_j = max(min_j, clock_{j-1} + 1)
              = rank_j + max_{i <= j}(max(prior+1, min_i) - rank_i)

  a segmented max-scan of ``max(prior+1, min) - rank`` — one sort, one
  cummax, one scatter.  Vote ranges are born compressed: process p votes
  ``(prev_end + 1, clock_j)`` per command (votes.rs try_compress shapes).

* ``stable_clocks`` — the tensor twin of ``VotesTable::stable_clock``
  (fantoch_ps/src/executor/table/mod.rs:247-270) over all key tables at
  once: sort the per-process vote frontiers along the process axis and take
  the ``(n - threshold)``-th column.

Both are shape-static, fully jittable, and batch-friendly: one kernel
launch replaces B hash-map bumps / K BTree walks.

Clock width: device clocks are **31-bit windowed**.  Raw wall-clock micros
(Newt's real-time mode) overflow int32 after ~35 minutes, so callers must
rebase device clocks against a window floor before the kernel — the
natural floor is the GC'd stable clock the protocol already tracks, and
timestamps are only ever compared within a window (votes below the stable
floor are collected; proposals are bounded by floor + in-flight commands).
The host twins (table_clocks.py) use unbounded Python ints and need no
rebasing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from fantoch_tpu.core.compile_cache import register_program

_WINDOW_MAX = (1 << 31) - 1


def next_pow2(n: int) -> int:
    """Smallest power of two >= n — the shared padding schedule of every
    table-plane caller (key tables, vote columns, batch rows), so XLA
    compiles O(log) distinct programs as capacities grow."""
    p = 1
    while p < n:
        p *= 2
    return p


class ClockWindow:
    """31-bit device-clock window over unbounded host clocks.

    Owns the rebasing the module docstring demands of callers: host-side
    clocks are int64 (Newt's real-time mode uses wall-clock micros, which
    overflow int32 after ~35 minutes); device kernels see
    ``clock - floor`` as int32.  The floor advances monotonically with the
    protocol's GC'd stable clock — every *live* comparison happens above
    it, so subtracting it is order-preserving.

    ``advance`` returns the shift to apply to device-resident clock tables
    (see :func:`shift_table`); entries at or below the new floor clamp to
    0, which keeps proposal semantics (``max(prior + 1, min)``) because a
    floor-or-older prior constrains nothing above the floor.
    """

    __slots__ = ("_floor",)

    def __init__(self, floor: int = 0):
        assert floor >= 0
        self._floor = int(floor)

    @property
    def floor(self) -> int:
        return self._floor

    def rebase(self, values) -> np.ndarray:
        """Host int64 clocks -> int32 device clocks (values - floor).

        Zero stays zero (the \"no clock yet\" bottom), everything else must
        lie in (floor, floor + 2^31)."""
        values = np.asarray(values, dtype=np.int64)
        out = np.where(values == 0, 0, values - self._floor)
        # strict: a clock exactly at the floor would alias the bottom (0)
        assert (out[values != 0] > 0).all(), (
            f"clock at or below the window floor {self._floor}: "
            f"min {values.min()}"
        )
        assert (out <= _WINDOW_MAX).all(), (
            f"clock overflows the 31-bit window above floor {self._floor}: "
            f"max {values.max()} (advance the window)"
        )
        return out.astype(np.int32)

    def restore(self, device_values) -> np.ndarray:
        """Device int32 clocks -> host int64 clocks (values + floor)."""
        vals = np.asarray(device_values, dtype=np.int64)
        return np.where(vals == 0, 0, vals + self._floor)

    def advance(self, new_floor: int) -> int:
        """Move the floor forward (monotone); returns the int32 shift to
        subtract from device-resident clock tables."""
        new_floor = int(new_floor)
        assert new_floor >= self._floor, "window floor is monotone"
        shift = new_floor - self._floor
        assert shift <= _WINDOW_MAX
        self._floor = new_floor
        return shift


@jax.jit
def shift_table(table: jax.Array, shift) -> jax.Array:
    """Rebase a device-resident clock table after ``ClockWindow.advance``:
    entries at or below the new floor clamp to 0 (no constraint)."""
    return jnp.maximum(table - jnp.int32(shift), 0)


def _seg_max_combiner(a, b):
    """Associative combiner for segmented running max: keep the right
    operand's value unless both sides share a segment — no magic offsets,
    no overflow for any clock magnitude."""
    a_seg, a_val = a
    b_seg, b_val = b
    return b_seg, jnp.where(a_seg == b_seg, jnp.maximum(a_val, b_val), b_val)


def segmented_running_max(seg_id: jax.Array, values: jax.Array, axis: int = 0):
    """Running max of ``values`` within segments of equal ``seg_id`` along
    ``axis`` (segments must be contiguous along that axis).  The shared
    core of the proposal kernels here and the mesh-wide proposal of
    parallel/mesh_step.py; ``seg_id`` broadcasts against ``values``."""
    seg = jnp.broadcast_to(seg_id, values.shape)
    _, running = jax.lax.associative_scan(
        _seg_max_combiner, (seg, values), axis=axis
    )
    return running


def _proposal_core(
    prior: jax.Array,  # int32[K]
    key: jax.Array,  # int32[B]
    min_clock: jax.Array,  # int32[B]
):
    """Traceable body of :func:`batched_clock_proposal` — shared with the
    fused table-round kernels below, which inline it inside one dispatch."""
    batch = key.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)

    # group commands by key, preserving batch order inside groups
    perm = jnp.argsort(key, stable=True).astype(jnp.int32)
    k_sorted = key[perm]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), k_sorted[1:] != k_sorted[:-1]]
    )
    # rank within the key group
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    group_first = jnp.where(seg_start, idx, 0)
    group_first = jax.lax.associative_scan(jnp.maximum, group_first)
    rank = idx - group_first

    base = jnp.maximum(prior[k_sorted] + 1, min_clock[perm])  # max(prior+1, min)
    # segmented running max of (base - rank), resetting at segment starts
    running = segmented_running_max(seg_id, base - rank)
    clock_sorted = rank + running

    clock = jnp.zeros((batch,), jnp.int32).at[perm].set(clock_sorted)
    # voter's range start: previous clock on this key + 1
    prev_clock_sorted = jnp.where(
        seg_start, prior[k_sorted], jnp.roll(clock_sorted, 1)
    )
    vote_start = jnp.zeros((batch,), jnp.int32).at[perm].set(prev_clock_sorted + 1)

    new_prior = prior.at[key].max(clock)
    return clock, vote_start, new_prior


@jax.jit
def batched_clock_proposal(
    prior: jax.Array,  # int32[K] — key clock before the batch
    key: jax.Array,  # int32[B] — key bucket per command
    min_clock: jax.Array,  # int32[B] — proposal lower bound (0 if none)
):
    """Returns ``(clock[B], vote_start[B], new_prior[K])``.

    ``clock`` is the proposed timestamp per command; the voter's consumed
    range for command i is ``(vote_start[i], clock[i])``; ``new_prior`` is
    the key-clock table after the whole batch (== the last clock per key).
    Batch order is proposal order within each key (the worker's arrival
    order, as in the sequential reference).
    """
    return _proposal_core(prior, key, min_clock)


@functools.partial(jax.jit, donate_argnums=(0,))
def resident_clock_proposal(
    prior: jax.Array,  # int32[K], DONATED — stays device-resident
    key: jax.Array,
    min_clock: jax.Array,
):
    """:func:`batched_clock_proposal` with the key-clock table donated:
    callers thread ``new_prior`` into the next call and the table never
    crosses the host boundary between batches (the mesh_step donation
    pattern applied to the proposal plane)."""
    return _proposal_core(prior, key, min_clock)


@functools.partial(jax.jit, donate_argnums=(0,))
def resident_clock_bump(
    prior: jax.Array,  # int32[K], DONATED — stays device-resident
    idx: jax.Array,  # int32[M] — bumped buckets (pad rows use K-1)
    clock: jax.Array,  # int32[M] — bumped-to clock per bucket (pad: 0)
):
    """Fold host-side scalar clock bumps into the resident key-clock
    table WITHOUT dropping residency: a scatter-max of the bumped
    buckets' new clocks (bumps are monotone, so max == set here, and max
    keeps pad rows harmless).  This is what keeps live Newt's scalar
    detached-bumps between submit batches from degrading the proposal
    path to upload-per-batch: the table stays on device and only the
    O(bumps) columns cross the host boundary (the BENCH_DEV round-6
    "device-side bump kernel" note, shipped)."""
    return prior.at[idx].max(clock)


@functools.partial(jax.jit, static_argnames=("threshold",))
def stable_clocks(frontiers: jax.Array, *, threshold: int) -> jax.Array:
    """Stable clock per key: the ``(n - threshold)``-th smallest of the n
    per-process vote frontiers (``int32[K, n] -> int32[K]``)."""
    n = frontiers.shape[1]
    assert threshold <= n
    return jnp.sort(frontiers, axis=1)[:, n - threshold]


# ---------------------------------------------------------------------------
# Device-resident votes-table plane: the commit path as donated dispatches.
#
# The host twin of the vote state is one RangeEventSet per (key, process)
# (executor/table.py VotesTable._votes): sorted disjoint non-adjacent
# ranges whose *frontier* (largest contiguous voted prefix) feeds the
# stability order statistic.  On device the state is the frontier matrix
# ``int32[K, n]`` alone; a merged vote run that lands beyond a frontier
# gap cannot advance it and is returned to the caller as *residual* —
# the caller re-feeds residuals with the next batch, so once the gap
# fills the frontier catches up exactly as the RangeEventSet would.
# After interval-merging, runs per (key, process) are disjoint and
# non-adjacent, so AT MOST ONE run per group can extend the frontier in
# a batch (the next run starts > extended_end + 1 by construction) —
# which is what makes the update a single scatter-max, no iteration.
# ---------------------------------------------------------------------------


def _votes_commit_core(
    frontier: jax.Array,  # int32[K, n]
    vkey: jax.Array,  # int32[V]
    vby: jax.Array,  # int32[V]
    vstart: jax.Array,  # int32[V]
    vend: jax.Array,  # int32[V]
    valid: jax.Array,  # bool[V]
    *,
    threshold: int,
):
    """Traceable body of :func:`fused_votes_commit` — shared with the
    Pallas table kernel (ops/pallas_resolve.py), which traces this same
    program inside one VMEM-resident kernel body so the two routes are
    bit-for-bit by construction."""
    K, n = frontier.shape
    V = vkey.shape[0]
    int_min = jnp.iinfo(jnp.int32).min
    slot = jnp.arange(V, dtype=jnp.int32)

    # sort by (group, start); invalid rows get a shared out-of-range group
    gid = jnp.where(valid, vkey * n + vby, K * n)
    order = jnp.lexsort((vstart, gid)).astype(jnp.int32)
    g = gid[order]
    s = vstart[order]
    e = vend[order]
    valid_s = valid[order]

    # interval merge within each group: runs break where a start clears
    # the group's running max end by more than 1 (classic sorted-interval
    # merge, the host twin of handle_batch_arrays' numpy coalescing)
    grp_start = jnp.concatenate([jnp.ones((1,), bool), g[1:] != g[:-1]])
    run_max_end = segmented_running_max(g, e)
    prev_max = jnp.roll(run_max_end, 1)
    new_run = grp_start | (s > prev_max + 1)
    rid = jnp.cumsum(new_run.astype(jnp.int32)) - 1  # [V], non-decreasing

    # per-run columns: end = scatter-max, head position = scatter-max of
    # the (unique-per-run) head index, everything else gathers at head
    run_end = jnp.full((V,), int_min, jnp.int32).at[rid].max(e)
    run_head = jnp.zeros((V,), jnp.int32).at[rid].max(
        jnp.where(new_run, slot, 0)
    )
    num_runs = rid[V - 1] + 1
    run_exists = slot < num_runs
    run_valid = run_exists & valid_s[run_head]
    run_key = jnp.where(run_valid, vkey[order][run_head], 0)
    run_by = jnp.where(run_valid, vby[order][run_head], 0)
    run_start = s[run_head]

    # frontier update: a run extends iff it touches the contiguous prefix
    f0 = frontier[run_key, run_by]
    extends = run_valid & (run_start <= f0 + 1) & (run_end > f0)
    residual = run_valid & (run_start > f0 + 1) & (run_end > f0)
    new_frontier = frontier.at[run_key, run_by].max(
        jnp.where(extends, run_end, 0)
    )

    stable = jnp.sort(new_frontier, axis=1)[:, n - threshold]
    return new_frontier, stable, run_key, run_by, run_start, run_end, residual


@functools.partial(jax.jit, static_argnames=("threshold",), donate_argnums=(0,))
def fused_votes_commit_xla(
    frontier: jax.Array,  # int32[K, n], DONATED — resident vote frontiers
    vkey: jax.Array,  # int32[V] — key bucket per vote range
    vby: jax.Array,  # int32[V] — voting process, 0-based column index
    vstart: jax.Array,  # int32[V]
    vend: jax.Array,  # int32[V]
    valid: jax.Array,  # bool[V] — pad rows False
    *,
    threshold: int,
):
    """One dispatch for the executor side of the table plane: coalesce
    vote ranges per (key, process), advance the resident frontiers, and
    compute every key's stable clock.

    Returns ``(new_frontier[K, n], stable[K], run_key[V], run_by[V],
    run_start[V], run_end[V], residual[V])``: the ``run_*`` columns hold
    the merged vote runs (one slot per run, invalid slots have
    ``residual`` False) and ``residual`` marks runs that start beyond
    the frontier gap — the caller buffers those and re-feeds them with
    the next batch (RangeEventSet semantics preserved across batches).
    """
    return _votes_commit_core(
        frontier, vkey, vby, vstart, vend, valid, threshold=threshold
    )


register_program("votes_commit_xla", fused_votes_commit_xla)


def fused_votes_commit(frontier, vkey, vby, vstart, vend, valid, *, threshold):
    """Route one table-plane commit dispatch: the Pallas-fused kernel
    when :func:`fantoch_tpu.ops.pallas_resolve.pallas_enabled` says so
    (and the window fits VMEM), else the composed
    :func:`fused_votes_commit_xla`.  Same signature, donation, and
    bit-for-bit 7-tuple either way (the residual-column protocol is
    part of the contract)."""
    from fantoch_tpu.ops import pallas_resolve as pr

    args = (frontier, vkey, vby, vstart, vend, valid)
    if pr.pallas_enabled() and pr._fits_vmem(frontier, vkey, vstart, vend):
        return pr.route_dispatch(
            "votes_commit", pr.votes_commit_pallas, fused_votes_commit_xla,
            args, {"threshold": threshold},
        )
    return fused_votes_commit_xla(*args, threshold=threshold)


def _fused_round_core(prior, frontier, key, min_clock, threshold, voters):
    """One full table round in-trace: proposal + contiguous vote
    application + stability.  The dense serving regime: the first
    ``voters`` processes vote every consumed range each round, so the
    per-key merged vote run is ``(prior + 1, new_prior)`` — contiguous
    with a voter's frontier iff that frontier already reached ``prior``.
    Voters with a gap (``gaps`` counts them) do NOT advance — callers
    fall back to the exact residual-tracking path when gaps appear."""
    K, n = frontier.shape
    clock, vote_start, new_prior = _proposal_core(prior, key, min_clock)
    touched = jnp.zeros((K,), bool).at[key].set(True)
    voter = jnp.arange(n, dtype=jnp.int32) < voters  # [n]
    contiguous = frontier >= prior[:, None]  # [K, n]
    lane = touched[:, None] & voter[None, :]
    new_frontier = jnp.where(
        lane & contiguous,
        jnp.maximum(frontier, new_prior[:, None]),
        frontier,
    )
    gaps = (lane & ~contiguous).sum().astype(jnp.int32)
    stable = jnp.sort(new_frontier, axis=1)[:, n - threshold]
    executable = clock <= stable[key]
    return new_prior, new_frontier, clock, vote_start, executable, gaps


@functools.partial(
    jax.jit, static_argnames=("threshold", "voters"), donate_argnums=(0, 1)
)
def fused_table_round_xla(
    prior: jax.Array,  # int32[K], DONATED
    frontier: jax.Array,  # int32[K, n], DONATED
    key: jax.Array,  # int32[B]
    min_clock: jax.Array,  # int32[B]
    *,
    threshold: int,
    voters: int,
):
    """Proposal + vote coalescing + frontier update + stability as ONE
    donated dispatch (the full Newt commit round for a batch of
    single-key commands in the dense all-votes regime).  Returns
    ``(new_prior, new_frontier, clock[B], vote_start[B], executable[B],
    gaps[])``; callers must keep the last key bucket as a scratch/pad
    bucket (the BatchedKeyClocks convention) if they pad batches."""
    return _fused_round_core(prior, frontier, key, min_clock, threshold, voters)


register_program("table_round_xla", fused_table_round_xla)


def fused_table_round(prior, frontier, key, min_clock, *, threshold, voters):
    """Route one dense table round: the Pallas-fused kernel when
    :func:`fantoch_tpu.ops.pallas_resolve.pallas_enabled` says so (and
    the tables fit VMEM), else the composed
    :func:`fused_table_round_xla`.  Bit-for-bit either way."""
    from fantoch_tpu.ops import pallas_resolve as pr

    args = (prior, frontier, key, min_clock)
    kwargs = {"threshold": threshold, "voters": voters}
    if pr.pallas_enabled() and pr._fits_vmem(prior, frontier, key):
        return pr.route_dispatch(
            "table_round", pr.table_round_pallas, fused_table_round_xla,
            args, kwargs,
        )
    return fused_table_round_xla(*args, **kwargs)


@functools.partial(
    jax.jit, static_argnames=("threshold", "voters"), donate_argnums=(0, 1)
)
def fused_table_rounds(
    prior: jax.Array,  # int32[K], DONATED
    frontier: jax.Array,  # int32[K, n], DONATED
    keys: jax.Array,  # int32[S, B] — S chained batches
    min_clocks: jax.Array,  # int32[S, B]
    *,
    threshold: int,
    voters: int,
):
    """``lax.scan`` chain of :func:`fused_table_round`: S batches commit
    in ONE dispatch, amortizing the host round-trip the same way the
    graph bench's chained in-dispatch resolves do.  Returns
    ``(prior, frontier, clock[S, B], vote_start[S, B], executable[S, B],
    gaps[S])``."""

    def body(carry, xs):
        prior, frontier = carry
        key, mc = xs
        new_prior, new_frontier, clock, vote_start, executable, gaps = (
            _fused_round_core(prior, frontier, key, mc, threshold, voters)
        )
        return (new_prior, new_frontier), (clock, vote_start, executable, gaps)

    (prior, frontier), (clock, vote_start, executable, gaps) = jax.lax.scan(
        body, (prior, frontier), (keys, min_clocks)
    )
    return prior, frontier, clock, vote_start, executable, gaps
