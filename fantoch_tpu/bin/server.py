"""Server binary: boot one protocol process of a cluster.

Reference: fantoch_ps/src/bin/common/protocol.rs:64-368 (`run::<P>()` and
the clap flag set) — protocol selection is a flag here instead of one
binary per protocol.

Example (3-process localhost EPaxos, process 1):
    python -m fantoch_tpu.bin.server --protocol epaxos --id 1 --shard-id 0 \\
        --port 7001 --client-port 8001 \\
        --addresses 2=127.0.0.1:7002,3=127.0.0.1:7003 \\
        --sorted 1:0,2:0,3:0 -n 3 -f 1
"""

from __future__ import annotations

import argparse
import asyncio

from fantoch_tpu.bin.common import (
    add_config_flags,
    config_from_args,
    force_platform_from_env,
    maybe_log_file,
    parse_peer,
    parse_sorted,
    protocol_by_name,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.server", description=__doc__
    )
    parser.add_argument("--protocol", required=True,
                        help="basic|epaxos|atlas|newt|caesar|fpaxos; with "
                        "--device-step the protocol round runs as one device "
                        "program: 'newt' the timestamp-consensus round, "
                        "'caesar' the timestamp+predecessors round, 'fpaxos' "
                        "the leader-based slot round, anything else the "
                        "EPaxos-style dep-commit round")
    parser.add_argument("--id", type=int, default=None,
                        help="process id (required without --device-step)")
    parser.add_argument("--shard-id", type=int, default=0)
    parser.add_argument("--ip", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=None, help="peer port")
    parser.add_argument("--client-port", type=int, required=True)
    parser.add_argument(
        "--device-step",
        action="store_true",
        help="serve through the device-resident protocol step "
        "(run/device_runner.py): the whole commit+execute round is one "
        "jit program over a (replica x batch) mesh; no TCP peer mesh",
    )
    parser.add_argument("--device-batch", type=int, default=256,
                        help="compiled device batch size (--device-step)")
    parser.add_argument("--device-key-buckets", type=int, default=4096)
    parser.add_argument("--device-key-width", type=int, default=1,
                        help="max conflict-key buckets per command")
    parser.add_argument(
        "--device-pipeline", choices=["auto", "on", "off"], default="auto",
        help="dispatch/drain overlap for saturated serving (auto = on for "
        "non-CPU backends, or whenever a pipeline depth was requested; "
        "overlap needs a compute resource besides the host cores).  The "
        "in-flight depth is the --serving-pipeline-depth config flag "
        "(one knob: flag > FANTOCH_SERVING_PIPELINE_DEPTH env > 1)")
    parser.add_argument("--device-pending", type=int, default=256,
                        help="device pending-buffer capacity")
    parser.add_argument(
        "--multihost", action="store_true",
        help="build the device mesh topology-aware for multi-host slices "
        "(parallel/multihost.py): hosts on the replica axis (quorum "
        "fan-ins ride DCN), each host's chips on the batch axis (sorts "
        "ride ICI); bootstraps jax.distributed when a coordinator is "
        "configured, degrades to the single-host mesh otherwise")
    parser.add_argument(
        "--addresses",
        default=None,
        help="comma list of pid=host:port[:delay_ms] for every peer this "
        "process connects to (own-shard peers + closest process of each "
        "other shard); delay_ms adds an artificial FIFO delay line "
        "(delay.rs:6-39)",
    )
    parser.add_argument(
        "--sorted",
        default=None,
        help="distance-sorted 'pid:shard,...' process list (self first); "
        "omit with --ping-sort to measure instead (ping.rs:13-78)",
    )
    parser.add_argument("--ping-sort", action="store_true")
    add_config_flags(parser)
    parser.add_argument("--workers", type=int, default=1)
    parser.add_argument("--executors", type=int, default=1)
    parser.add_argument("--multiplexing", type=int, default=1,
                        help="TCP connections per peer (random writer pick, "
                        "process.rs:71-97)")
    parser.add_argument("--metrics-file", default=None,
                        help="periodic crash-consistent snapshots; gzip+pickle "
                        "ProcessMetrics normally, JSON round/path tallies "
                        "under --device-step")
    parser.add_argument("--metrics-interval", type=int, default=5000, metavar="MS")
    parser.add_argument("--telemetry-file", default=None,
                        help="live windowed telemetry series "
                        "(observability/timeseries.py): one JSONL ring of "
                        "per-window rates + histogram snapshots; `obs "
                        "watch` renders it live")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="Prometheus-text exposition endpoint "
                        "(observability/exposition.py): GET /metrics "
                        "scrapes the live sample, GET /profile?ms=N "
                        "captures an on-demand jax.profiler device trace "
                        "next to the telemetry file (SIGUSR2 triggers the "
                        "same capture); 0 = OS-assigned")
    parser.add_argument(
        "--heartbeat-interval", type=float, default=1.0, metavar="S",
        help="peer failure-detector probe interval (seconds)")
    parser.add_argument(
        "--heartbeat-misses", type=int, default=8,
        help="silent intervals before a peer is declared lost; raise on "
             "contended machines (testbeds sharing one core) so CPU "
             "starvation does not read as peer death")
    parser.add_argument("--trace-file", default=None, metavar="PATH",
                        help="per-dot lifecycle span log (JSONL; needs "
                        "--trace RATE > 0): message edges + spans that "
                        "`bin/obs.py critpath` stitches across processes")
    parser.add_argument("--flight-dir", default=None, metavar="DIR",
                        help="where --flight-recorder dumps "
                        "flight_p<pid>.json black boxes (default: next "
                        "to the trace/telemetry/metrics file)")
    parser.add_argument("--execution-log", default=None)
    parser.add_argument("--wal-dir", default=None, metavar="DIR",
                        help="durable command log + snapshots (run/wal.py): "
                        "on a restart with the same dir the server recovers "
                        "(snapshot + tail replay) and rejoins via MSync "
                        "instead of starting empty")
    parser.add_argument("--wal-snapshot-interval", type=int, default=2000,
                        metavar="MS", help="WAL snapshot cadence")
    parser.add_argument("--tracer-show-interval", type=int, default=None, metavar="MS")
    parser.add_argument("--log-file", default=None)
    return parser


async def serve_device_step(args: argparse.Namespace) -> None:
    """The TPU serving path: one server, the protocol round on-device."""
    from fantoch_tpu.run.device_runner import DeviceRuntime

    protocol_by_name(args.protocol)  # validate the label even when unused
    config = config_from_args(args)
    process_id = args.id if args.id is not None else 1
    mesh = None
    if args.multihost:
        from fantoch_tpu.parallel.multihost import (
            distributed_init,
            make_multihost_mesh,
        )

        distributed_init()
        # the mesh is sized by TOTAL replica rows: the sharded device state
        # holds n rows per shard in shard-major order (_init_sharded_mesh),
        # so validating against config.n alone would under-count the mesh
        mesh = make_multihost_mesh(
            num_replicas=config.n * config.shard_count,
            shard_count=config.shard_count,
        )
    runtime = DeviceRuntime(
        config,
        (args.ip, args.client_port),
        protocol=args.protocol,
        process_id=process_id,
        batch_size=args.device_batch,
        key_buckets=args.device_key_buckets,
        key_width=args.device_key_width,
        pending_capacity=args.device_pending,
        monitor_execution_order=config.executor_monitor_execution_order,
        metrics_file=args.metrics_file,
        metrics_interval_ms=args.metrics_interval,
        pipeline=None if args.device_pipeline == "auto"
        else args.device_pipeline == "on",
        mesh=mesh,
        telemetry_file=args.telemetry_file,
        metrics_port=args.metrics_port,
        trace_file=args.trace_file,
        flight_dir=args.flight_dir,
    )
    await runtime.start()
    _arm_profile_signal(args)
    _arm_flight_signal(runtime)
    print(
        f"p{process_id} (device-step, n={config.n}) serving clients on "
        f"{args.ip}:{args.client_port}"
        + (
            f"; /metrics on :{runtime.metrics_port}"
            if runtime.metrics_port is not None
            else ""
        ),
        flush=True,
    )
    try:
        await runtime.failed.wait()
        raise SystemExit(f"p{process_id} failed: {runtime.failure!r}")
    finally:
        # runs under task cancellation too (Ctrl-C through asyncio.run):
        # short serves must still leave a final metrics snapshot
        if runtime.metrics_file is not None or runtime.telemetry is not None:
            runtime._emit_telemetry()


def _arm_flight_signal(runtime) -> None:
    """SIGUSR1 = dump the flight-recorder ring on demand (a black box
    without killing the run); no-op when the recorder is off."""
    if getattr(runtime, "flight", None) is None:
        return
    from fantoch_tpu.observability.recorder import install_flight_signal

    install_flight_signal(runtime.flight, runtime.flight_dir)


def _arm_profile_signal(args: argparse.Namespace) -> None:
    """SIGUSR2 = capture a 1s jax.profiler device trace next to the
    telemetry/metrics file (the no-port spelling of ``/profile?ms=N``)."""
    from fantoch_tpu.observability.exposition import (
        install_profile_signal,
        profile_output_dir,
    )

    install_profile_signal(
        profile_output_dir(args.telemetry_file, args.metrics_file)
    )


async def serve(args: argparse.Namespace) -> None:
    from fantoch_tpu.run.process_runner import ProcessRuntime

    if args.device_step:
        await serve_device_step(args)
        return
    if args.id is None or args.port is None or args.addresses is None:
        raise SystemExit(
            "--id, --port and --addresses are required without --device-step"
        )
    protocol_cls = protocol_by_name(args.protocol)
    config = config_from_args(args)

    peers = {}
    delays = {}
    for entry in args.addresses.split(","):
        pid, host, port, delay = parse_peer(entry)
        peers[pid] = (host, port)
        if delay is not None:
            delays[pid] = delay

    if args.sorted:
        sorted_processes = parse_sorted(args.sorted)
    else:
        if not args.ping_sort:
            raise SystemExit("--sorted or --ping-sort is required")
        # the address list carries no shard labels, so the provisional
        # all-own-shard list is only correct single-shard; multi-shard
        # topologies must say which peer serves which shard via --sorted
        if args.shard_count != 1:
            raise SystemExit(
                "--ping-sort without --sorted requires --shard-count 1; "
                "pass --sorted for multi-shard topologies"
            )
        # provisional order (self first); ping_sort re-sorts at startup
        sorted_processes = [(args.id, args.shard_id)] + [
            (pid, args.shard_id) for pid in sorted(peers)
        ]

    runtime = ProcessRuntime(
        protocol_cls,
        args.id,
        args.shard_id,
        config,
        listen_addr=(args.ip, args.port),
        client_addr=(args.ip, args.client_port),
        peers=peers,
        sorted_processes=sorted_processes,
        workers=args.workers,
        executors=args.executors,
        multiplexing=args.multiplexing,
        peer_delays=delays or None,
        ping_sort=args.ping_sort,
        metrics_file=args.metrics_file,
        metrics_interval_ms=args.metrics_interval,
        execution_log=args.execution_log,
        tracer_show_interval_ms=args.tracer_show_interval,
        heartbeat_interval_s=args.heartbeat_interval,
        heartbeat_misses=args.heartbeat_misses,
        wal_dir=args.wal_dir,
        wal_snapshot_interval_ms=args.wal_snapshot_interval,
        telemetry_file=args.telemetry_file,
        metrics_port=args.metrics_port,
        trace_file=args.trace_file,
        flight_dir=args.flight_dir,
    )
    await runtime.start()
    _arm_profile_signal(args)
    _arm_flight_signal(runtime)
    print(
        f"p{args.id} ({args.protocol}) up on {args.ip}:{args.port}"
        + (
            f"; /metrics on :{runtime.metrics_port}"
            if runtime.metrics_port is not None
            else ""
        ),
        flush=True,
    )
    await runtime.failed.wait()
    raise SystemExit(f"p{args.id} failed: {runtime.failure!r}")


def main(argv=None) -> None:
    force_platform_from_env()
    args = build_parser().parse_args(argv)
    maybe_log_file(args.log_file)
    try:
        asyncio.run(serve(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
