"""CLI entry points (the fantoch_ps/src/bin analog).

One server binary covering every protocol via ``--protocol`` (the
reference monomorphizes one binary per protocol x variant,
fantoch_ps/src/bin/{atlas,epaxos,...}.rs over common/protocol.rs; a flag
is the Python-idiomatic equivalent), a client binary, and the aux tools:
simulation sweep, execution-log replay, and shard-distribution analysis.

Usage:
    python -m fantoch_tpu.bin.server --protocol epaxos --id 1 ...
    python -m fantoch_tpu.bin.client --ids 1-3 --addresses 0=127.0.0.1:7001 ...
    python -m fantoch_tpu.bin.simulation --protocol newt --clients 10
    python -m fantoch_tpu.bin.replay --log execution_p1.log --protocol epaxos
    python -m fantoch_tpu.bin.shard_distribution --shard-count 4
"""
