"""Scenario observatory CLI: expand / run declarative sweep specs.

The fantoch_exp driver analog over exp/scenarios.py: a JSON spec file
declares the whole cross product (protocol x n/f x fault plan x skew x
rate ladder x knobs x placement) and this tool either prints its
deterministic expansion (``expand`` — byte-identical for the same spec,
the reproducibility contract) or executes every cell and emits the
throughput-latency curve artifacts (``run`` — per-cell obs dirs,
``curves.json``, rendered PNG).  Inspect results with
``python -m fantoch_tpu.bin.obs curves <out dir>``.

    python -m fantoch_tpu.bin.scenario expand spec.json
    python -m fantoch_tpu.bin.scenario run spec.json --out /tmp/obs
"""

from __future__ import annotations

import argparse
import sys


def cmd_expand(args) -> int:
    from fantoch_tpu.exp.scenarios import canonical_expansion, load_spec

    text = canonical_expansion(load_spec(args.spec))
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
            fh.write("\n")
    else:
        print(text)
    return 0


def cmd_run(args) -> int:
    from fantoch_tpu.exp.scenarios import load_spec, run_scenario

    doc = run_scenario(
        load_spec(args.spec), args.out, render=not args.no_render
    )
    failed = 0
    for curve in doc["curves"]:
        label = f"{curve['protocol']} n={curve['n']} f={curve['f']}"
        knee = curve.get("knee")
        knee_text = (
            f"knee at offered {knee['offered_cmds_per_s']}/s "
            f"(goodput {knee['goodput_cmds_per_s']}/s)"
            if knee is not None
            else "unsaturated"
        )
        print(f"{label}: {len(curve['points'])} points, {knee_text}")
        failed += sum(
            1 for verdict in curve["slo"]
            if verdict["checks"] and not verdict["pass"]
        )
    print(f"artifacts in {args.out} (curves.json"
          + ("" if args.no_render else " + curves.png") + ")")
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="scenario", description="declarative scenario sweeps"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser(
        "expand", help="print the deterministic run-matrix expansion"
    )
    p.add_argument("spec", help="scenario spec JSON file")
    p.add_argument("--out", help="write the expansion here instead")
    p.set_defaults(fn=cmd_expand)

    p = sub.add_parser(
        "run", help="execute every cell and emit saturation curves"
    )
    p.add_argument("spec", help="scenario spec JSON file")
    p.add_argument("--out", required=True, help="output directory")
    p.add_argument("--no-render", action="store_true",
                   help="skip the PNG (curves.json only)")
    p.set_defaults(fn=cmd_run)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
