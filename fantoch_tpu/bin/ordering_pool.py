"""CLI front for the multi-process ordering pool: measure aggregate
ordering throughput across N key-sharded worker processes.

The process-granularity twin of the reference's 16-worker production
defaults (fantoch/src/run/pool.rs:115-124 +
fantoch_exp/src/config.rs:21-29): one front shards a workload by key
bucket, N OS processes each order their shard through their own
BatchedDependencyGraph, and the front reports the aggregate.

    python -m fantoch_tpu.bin.ordering_pool --workers 4 --commands 1000000
"""

from __future__ import annotations

import argparse
import json
import time


def main() -> None:
    parser = argparse.ArgumentParser("fantoch_tpu.bin.ordering_pool")
    parser.add_argument("--workers", type=int, default=4)
    parser.add_argument("--commands", type=int, default=1 << 20)
    parser.add_argument("--conflict", type=float, default=0.5)
    args = parser.parse_args()

    from fantoch_tpu.bin.common import force_platform_from_env

    force_platform_from_env()
    import multiprocessing as mp

    import numpy as np

    import bench  # repo-root module: shared workload builder
    from fantoch_tpu.run.local_pool import OrderingPool

    key, dep, src, seq = bench.build_workload(args.commands, args.conflict)
    warm_key, warm_dep, warm_src, warm_seq = bench.build_workload(
        args.commands, args.conflict, seed=7
    )
    shards = OrderingPool.shard_columns(
        key, src.astype(np.int64), seq.astype(np.int64) + 1,
        dep.astype(np.int64), args.workers,
    )
    warm = OrderingPool.shard_columns(
        warm_key, warm_src.astype(np.int64),
        warm_seq.astype(np.int64) + 1 + args.commands,
        warm_dep.astype(np.int64), args.workers,
    )
    with OrderingPool(args.workers) as pool:
        pool.prepare(max(len(s[0]) for s in shards + warm))
        pool.run_shards(warm)
        t0 = time.perf_counter()
        orders = pool.run_shards(shards)
        dt = time.perf_counter() - t0
    executed = sum(len(s) for s, _ in orders)
    assert executed == args.commands
    print(
        json.dumps(
            {
                "workers": args.workers,
                "cpus": mp.cpu_count(),
                "commands": args.commands,
                "wall_ms": round(dt * 1000.0, 1),
                "cmds_per_s": int(args.commands / dt),
            }
        )
    )


if __name__ == "__main__":
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    main()
