"""Replay an execution log through a fresh executor.

Reference: fantoch_ps/src/bin/graph_executor_replay.rs:14-38 — offline
debugging of executor ordering from a log written with --execution-log.

    python -m fantoch_tpu.bin.replay --log execution_p1.log \\
        --protocol epaxos --id 1 -n 3 -f 1
"""

from __future__ import annotations

import argparse
import json

from fantoch_tpu.bin.common import (
    add_config_flags,
    config_from_args,
    force_platform_from_env,
    protocol_by_name,
)


def main(argv=None) -> None:
    force_platform_from_env()
    parser = argparse.ArgumentParser(prog="fantoch_tpu.bin.replay", description=__doc__)
    parser.add_argument("--log", required=True)
    parser.add_argument("--protocol", required=True)
    parser.add_argument("--id", type=int, required=True)
    parser.add_argument("--shard-id", type=int, default=0)
    add_config_flags(parser)
    args = parser.parse_args(argv)

    from fantoch_tpu.run.observe import replay_execution_log

    summary = replay_execution_log(
        args.log,
        protocol_by_name(args.protocol),
        args.id,
        args.shard_id,
        config_from_args(args),
    )
    print(
        json.dumps(
            {
                "batches_handled": summary["batches_handled"],
                "results": summary["results"],
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
