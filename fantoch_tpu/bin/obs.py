"""Trace tooling CLI: summarize / convert / diff span logs.

The lifecycle tracing plane (fantoch_tpu/observability) writes JSONL
span logs; this CLI turns them into answers:

    # per-stage latency breakdown (p50/p95/p99 per segment, end-to-end)
    python -m fantoch_tpu.bin.obs summarize trace.jsonl [more.jsonl ...]

    # Chrome/Perfetto trace-event JSON (load at ui.perfetto.dev)
    python -m fantoch_tpu.bin.obs to-perfetto trace.jsonl -o trace.json

    # structural diff of two traces (same-seed sim runs must be empty)
    python -m fantoch_tpu.bin.obs diff a.jsonl b.jsonl

``summarize`` accepts several logs at once (a localhost cluster writes
one per process plus the client plane) and assembles spans across them.
No reference counterpart: fantoch's metrics_logger/tracer only ship
aggregates; this is the per-command attribution layer on top.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _load(paths: List[str]) -> List[Dict[str, Any]]:
    from fantoch_tpu.observability.tracer import read_trace

    events: List[Dict[str, Any]] = []
    for path in paths:
        events.extend(read_trace(path))
    return events


def cmd_summarize(args) -> int:
    from fantoch_tpu.observability.report import summarize

    out = summarize(_load(args.trace))
    if args.json:
        print(json.dumps(out, sort_keys=True))
        return 0
    print(f"spans: {out['spans']}  events: {out['events']}")
    coverage = ", ".join(
        f"{stage}={count}" for stage, count in out["stage_coverage"].items()
    )
    print(f"stage coverage: {coverage}")
    if out["monotonic_violations"]:
        print(f"MONOTONIC VIOLATIONS: {out['monotonic_violations']}")
    print(f"{'segment':<22}{'count':>8}{'mean':>10}{'p50':>10}{'p95':>10}{'p99':>10}")
    rows = dict(out.get("segments", {}))
    if "end_to_end" in out:
        rows["end_to_end"] = out["end_to_end"]
    for name, row in rows.items():
        print(
            f"{name:<22}{row['count']:>8}"
            f"{row['mean_us'] / 1000:>9.2f}m"
            f"{row['p50_us'] / 1000:>9.2f}m"
            f"{row['p95_us'] / 1000:>9.2f}m"
            f"{row['p99_us'] / 1000:>9.2f}m"
        )
    counters = out.get("device_counters", {})
    for name, value in sorted(counters.items()):
        print(f"counter {name} = {value}")
    _print_overlap(counters)
    _print_overload(counters)
    _print_audit(counters)
    return 0


def _print_audit(counters) -> int:
    """One-line consistency-audit readout from the digest-exchange
    counters (Config.execution_digests): how many peer summaries were
    cross-checked, over how many keys, and whether any mismatch (a
    replica fork -> typed DivergenceError) was ever observed."""
    names = ("digest_checks", "digest_mismatches", "digest_keys")
    if not any(name in counters for name in names):
        return 0
    mismatches = int(counters.get("digest_mismatches", 0))
    parts = [
        f"digest checks {int(counters.get('digest_checks', 0))}",
        f"keys {int(counters.get('digest_keys', 0))}",
        f"mismatches {mismatches}" + (" (DIVERGED)" if mismatches else ""),
    ]
    print("audit: " + "  ".join(parts))
    return 0


def _print_overload(counters) -> int:
    """One-line overload-plane readout from the queue/shed counters
    (run/backpressure.py): worst queue depth high-watermark across
    processes, total sheds, and backpressure pauses — the signal that a
    run was (or was not) operating past its admission edge."""
    names = ("queue_depth_hwm", "shed_submissions", "backpressure_pauses")
    if not any(name in counters for name in names):
        return 0
    parts = [
        f"queue depth hwm {int(counters.get('queue_depth_hwm', 0))}",
        f"sheds {int(counters.get('shed_submissions', 0))}",
        f"backpressure pauses {int(counters.get('backpressure_pauses', 0))}",
    ]
    print("overload: " + "  ".join(parts))
    return 0


def _print_overlap(counters) -> int:
    """One-line dispatch/drain overlap readout from the per-dispatch
    device counters (run/pipeline.py): how the serving wall split
    between host batch assembly (dispatch), host drain (fetch + emit),
    and device-busy time — and the ``device_idle_frac`` the pipelined
    loop is meant to drive toward 0."""
    from fantoch_tpu.observability.device import derive_idle_frac

    if not any(k in counters for k in ("device_dispatch_ms", "device_busy_ms")):
        return 0
    counters = derive_idle_frac(dict(counters))
    dispatch = counters.get("device_dispatch_ms", 0.0)
    drain = counters.get("device_drain_ms", 0.0)
    fetch = counters.get("device_fetch_ms", 0.0)
    busy = counters.get("device_busy_ms", 0.0)
    span = counters.get("device_span_ms", 0.0)
    parts = [
        f"dispatch {dispatch:.1f}ms",
        f"drain {drain:.1f}ms (fetch {fetch:.1f}ms)",
    ]
    if span:
        parts.append(f"device busy {busy:.1f}ms of {span:.1f}ms span")
    if "device_idle_frac" in counters:
        parts.append(f"idle_frac {counters['device_idle_frac']:.3f}")
    depth = counters.get("device_pipeline_depth")
    if depth:
        parts.append(f"depth {int(depth)}")
    pipelined = counters.get("device_pipelined_rounds")
    if pipelined is not None:
        parts.append(f"pipelined_rounds {int(pipelined)}")
    print("device overlap: " + "  ".join(parts))
    return 0


def cmd_to_perfetto(args) -> int:
    from fantoch_tpu.observability.perfetto import write_perfetto

    count = write_perfetto(_load(args.trace), args.output)
    print(f"wrote {count} trace events to {args.output}")
    return 0


def cmd_diff(args) -> int:
    from fantoch_tpu.observability.report import diff_events
    from fantoch_tpu.observability.tracer import read_trace

    mismatches = diff_events(read_trace(args.a), read_trace(args.b))
    for line in mismatches:
        print(line)
    if not mismatches:
        print("traces identical")
        return 0
    return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="obs", description="dot-lifecycle trace tooling"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summarize", help="per-stage latency breakdown")
    p.add_argument("trace", nargs="+", help="JSONL span log(s)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.set_defaults(fn=cmd_summarize)

    p = sub.add_parser("to-perfetto", help="convert to trace-event JSON")
    p.add_argument("trace", nargs="+", help="JSONL span log(s)")
    p.add_argument("-o", "--output", required=True, help="output .json path")
    p.set_defaults(fn=cmd_to_perfetto)

    p = sub.add_parser("diff", help="structural diff of two span logs")
    p.add_argument("a")
    p.add_argument("b")
    p.set_defaults(fn=cmd_diff)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
