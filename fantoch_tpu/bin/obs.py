"""Observability CLI: summarize / convert / diff span logs, watch and
scrape live telemetry.

The lifecycle tracing plane (fantoch_tpu/observability) writes JSONL
span logs and the telemetry plane windowed series; this CLI turns them
into answers:

    # per-stage latency breakdown (p50/p95/p99 per segment, end-to-end)
    python -m fantoch_tpu.bin.obs summarize trace.jsonl [more.jsonl ...]

    # Chrome/Perfetto trace-event JSON (load at ui.perfetto.dev)
    python -m fantoch_tpu.bin.obs to-perfetto trace.jsonl -o trace.json

    # structural diff of two traces (same-seed sim runs must be empty)
    python -m fantoch_tpu.bin.obs diff a.jsonl b.jsonl

    # live terminal view of a cluster's telemetry (series files, an obs
    # dir, or /metrics endpoints; --once renders a single frame)
    python -m fantoch_tpu.bin.obs watch obs_dir/ 127.0.0.1:9090

    # one exposition scrape (raw Prometheus text, or parsed --json)
    python -m fantoch_tpu.bin.obs scrape 127.0.0.1:9090 --json

``summarize`` accepts several logs at once (a localhost cluster writes
one per process plus the client plane) and assembles spans across them.
No reference counterpart: fantoch's metrics_logger/tracer only ship
aggregates; this is the per-command attribution + live-telemetry layer
on top.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List


def _load(paths: List[str]) -> List[Dict[str, Any]]:
    """Load span logs (JSONL) and/or flight-recorder dumps (.json black
    boxes) into one event stream — the correlator consumes both alike."""
    from fantoch_tpu.observability.recorder import flight_events
    from fantoch_tpu.observability.tracer import read_trace

    events: List[Dict[str, Any]] = []
    for path in paths:
        if path.endswith(".json"):
            try:
                events.extend(flight_events([path]))
                continue
            except (AssertionError, ValueError, KeyError):
                pass  # not a flight dump: fall through to JSONL reading
        events.extend(read_trace(path))
    return events


def cmd_summarize(args) -> int:
    from fantoch_tpu.observability.report import summarize

    out = summarize(_load(args.trace))
    counters = out.get("device_counters")
    if counters and "device_busy_ms" in counters:
        # derived overlap metrics ride the machine-readable payload too,
        # so --json consumers get exactly what the human lines print
        # (CI smokes key on this instead of regex-scraping the text)
        from fantoch_tpu.observability.device import derive_idle_frac

        out["device_counters"] = counters = derive_idle_frac(dict(counters))
    if args.json:
        print(json.dumps(out, sort_keys=True))
        return 0
    print(f"spans: {out['spans']}  events: {out['events']}")
    coverage = ", ".join(
        f"{stage}={count}" for stage, count in out["stage_coverage"].items()
    )
    print(f"stage coverage: {coverage}")
    if out["monotonic_violations"]:
        print(f"MONOTONIC VIOLATIONS: {out['monotonic_violations']}")
    print(f"{'segment':<22}{'count':>8}{'mean':>10}{'p50':>10}{'p95':>10}{'p99':>10}")
    rows = dict(out.get("segments", {}))
    if "end_to_end" in out:
        rows["end_to_end"] = out["end_to_end"]
    for name, row in rows.items():
        print(
            f"{name:<22}{row['count']:>8}"
            f"{row['mean_us'] / 1000:>9.2f}m"
            f"{row['p50_us'] / 1000:>9.2f}m"
            f"{row['p95_us'] / 1000:>9.2f}m"
            f"{row['p99_us'] / 1000:>9.2f}m"
        )
    counters = out.get("device_counters", {})
    for name, value in sorted(counters.items()):
        print(f"counter {name} = {value}")
    _print_overlap(counters)
    _print_planes(counters)
    _print_compile(counters)
    _print_overload(counters)
    _print_audit(counters)
    return 0


def _print_compile(counters) -> int:
    """One-line XLA compile readout: how many backend compiles the run
    paid and their cumulative wall (observability/device.py) — a ~50s
    cold compile starving heartbeats is invisible in a count of 1."""
    if "jax_recompiles" not in counters and "jax_compile_ms" not in counters:
        return 0
    ms = counters.get("jax_compile_ms", 0.0)
    print(
        f"compile: {int(counters.get('jax_recompiles', 0))} XLA backend "
        f"compile(s), {ms / 1000:.1f}s cumulative wall"
    )
    return 0


def _print_planes(counters) -> int:
    """One line per resident device plane (table / pred / graph): how
    many fused dispatches, how many host->device window materializations
    (``resident_uploads`` — the residency invariant: one lazy initial
    plus compaction/grow/restore re-uploads, never one per batch), and
    the current slot capacity gauge."""
    shown = 0
    for prefix, label in (
        ("table_plane", "table plane"),
        ("pred_plane", "pred plane"),
        ("graph_plane", "graph plane"),
    ):
        if f"{prefix}_dispatches" not in counters:
            continue
        parts = [
            f"dispatches {int(counters.get(f'{prefix}_dispatches', 0))}",
            f"uploads {int(counters.get(f'{prefix}_resident_uploads', 0))}",
            f"kernel {counters.get(f'{prefix}_kernel_ms', 0.0):.1f}ms",
        ]
        cap = counters.get(f"{prefix}_slot_capacity")
        if cap is not None:
            parts.append(f"capacity {int(cap)}")
        # accelerator fault tolerance (executor/device_plane.py): the
        # max-folded health gauge plus failover/rebuild tallies and the
        # wall spent serving from the host twin
        health = counters.get(f"{prefix}_health")
        if health is not None:
            from fantoch_tpu.executor.device_plane import HEALTH_NAMES

            parts.append(f"health {HEALTH_NAMES[int(health)]}")
        failovers = int(counters.get(f"{prefix}_failovers", 0))
        rebuilds = int(counters.get(f"{prefix}_rebuilds", 0))
        if failovers or rebuilds:
            parts.append(f"failovers {failovers}")
            parts.append(f"rebuilds {rebuilds}")
            parts.append(
                f"degraded {counters.get(f'{prefix}_degraded_ms', 0.0):.1f}ms"
            )
        print(f"{label}: " + "  ".join(parts))
        shown += 1
    return shown


def _print_audit(counters) -> int:
    """One-line consistency-audit readout from the digest-exchange
    counters (Config.execution_digests): how many peer summaries were
    cross-checked, over how many keys, and whether any mismatch (a
    replica fork -> typed DivergenceError) was ever observed."""
    names = ("digest_checks", "digest_mismatches", "digest_keys")
    if not any(name in counters for name in names):
        return 0
    mismatches = int(counters.get("digest_mismatches", 0))
    parts = [
        f"digest checks {int(counters.get('digest_checks', 0))}",
        f"keys {int(counters.get('digest_keys', 0))}",
        f"mismatches {mismatches}" + (" (DIVERGED)" if mismatches else ""),
    ]
    print("audit: " + "  ".join(parts))
    return 0


def _print_overload(counters) -> int:
    """One-line overload-plane readout from the queue/shed counters
    (run/backpressure.py): worst queue depth high-watermark across
    processes, total sheds, and backpressure pauses — the signal that a
    run was (or was not) operating past its admission edge."""
    names = ("queue_depth_hwm", "shed_submissions", "backpressure_pauses")
    if not any(name in counters for name in names):
        return 0
    parts = [
        f"queue depth hwm {int(counters.get('queue_depth_hwm', 0))}",
        f"sheds {int(counters.get('shed_submissions', 0))}",
        f"backpressure pauses {int(counters.get('backpressure_pauses', 0))}",
    ]
    print("overload: " + "  ".join(parts))
    return 0


def _print_overlap(counters) -> int:
    """One-line dispatch/drain overlap readout from the per-dispatch
    device counters (run/pipeline.py): how the serving wall split
    between host batch assembly (dispatch), host drain (fetch + emit),
    and device-busy time — and the ``device_idle_frac`` the pipelined
    loop is meant to drive toward 0."""
    from fantoch_tpu.observability.device import derive_idle_frac

    if not any(k in counters for k in ("device_dispatch_ms", "device_busy_ms")):
        return 0
    counters = derive_idle_frac(dict(counters))
    dispatch = counters.get("device_dispatch_ms", 0.0)
    drain = counters.get("device_drain_ms", 0.0)
    fetch = counters.get("device_fetch_ms", 0.0)
    busy = counters.get("device_busy_ms", 0.0)
    span = counters.get("device_span_ms", 0.0)
    parts = [
        f"dispatch {dispatch:.1f}ms",
        f"drain {drain:.1f}ms (fetch {fetch:.1f}ms)",
    ]
    if span:
        parts.append(f"device busy {busy:.1f}ms of {span:.1f}ms span")
    if "device_idle_frac" in counters:
        parts.append(f"idle_frac {counters['device_idle_frac']:.3f}")
    depth = counters.get("device_pipeline_depth")
    if depth:
        parts.append(f"depth {int(depth)}")
    pipelined = counters.get("device_pipelined_rounds")
    if pipelined is not None:
        parts.append(f"pipelined_rounds {int(pipelined)}")
    print("device overlap: " + "  ".join(parts))
    return 0


def _scrape_url(target: str, timeout: float = 5.0) -> str:
    """Fetch one exposition endpoint.  ``host:port`` expands to
    ``http://host:port/metrics``."""
    import urllib.request

    url = target
    if "://" not in url:
        url = f"http://{url}"
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


def cmd_scrape(args) -> int:
    """One scrape per target: raw Prometheus text, or parsed ``--json``
    (``{metric: {"label=value,...": value}}``) for scripts."""
    from fantoch_tpu.observability.exposition import parse_prometheus

    out: Dict[str, Any] = {}
    for target in args.target:
        text = _scrape_url(target)
        if not args.json:
            print(text, end="")
            continue
        parsed = parse_prometheus(text)
        out[target] = {
            name: {
                ",".join(f"{k}={v}" for k, v in labels): value
                for labels, value in samples.items()
            }
            for name, samples in parsed.items()
        }
    if args.json:
        print(json.dumps(out, sort_keys=True))
    return 0


def _watch_sources(targets: List[str]) -> Dict[str, Dict[str, Any]]:
    """Latest telemetry window per source across every target: series
    files, obs directories (globbing ``telemetry_*.jsonl``), or live
    ``/metrics`` endpoints (parsed back into a window-shaped row)."""
    import glob

    from fantoch_tpu.observability.exposition import parse_prometheus
    from fantoch_tpu.observability.timeseries import latest_windows, read_series

    latest: Dict[str, Dict[str, Any]] = {}
    for target in targets:
        if os.path.isdir(target):
            paths = sorted(glob.glob(os.path.join(target, "telemetry_*.jsonl")))
        elif os.path.exists(target):
            paths = [target]
        else:
            # an endpoint: synthesize one window row from the live
            # sample.  A failed scrape (server restarting, typo'd path
            # falling through to the URL branch) degrades to an error
            # row — the live view must keep rendering, not die with a
            # traceback mid-watch
            try:
                parsed = parse_prometheus(_scrape_url(target))
            except Exception as exc:  # noqa: BLE001 — any scrape failure degrades
                latest[target] = {"src": target, "ctr": {}, "g": {},
                                  "rate": {}, "h": {}, "t": 0, "seq": -1,
                                  "err": str(exc)}
                continue
            ctr: Dict[str, float] = {}
            gauges: Dict[str, float] = {}
            for name, samples in parsed.items():
                value = next(iter(samples.values()))
                if name.startswith("fantoch_") and name.endswith("_total"):
                    ctr[name[len("fantoch_"):-len("_total")]] = value
                elif name.startswith("fantoch_") and not name.endswith(
                    ("_bucket", "_sum", "_count")
                ):
                    gauges[name[len("fantoch_"):]] = value
            latest[target] = {"src": target, "ctr": ctr, "g": gauges,
                              "rate": {}, "h": {}, "t": 0, "seq": -1}
            continue
        for path in paths:
            for src, window in latest_windows(read_series(path)).items():
                # several files may carry the same source name (one
                # client plane per pool): fall back to the file stem
                key = (
                    src
                    if src not in latest
                    else os.path.splitext(os.path.basename(path))[0]
                )
                latest[key] = window
    return latest


def _render_watch(latest: Dict[str, Dict[str, Any]]) -> str:
    """One table frame: per source, submit/reply rates, the client or
    end-to-end latency window, queue depth, sheds, device idle."""
    lines = [
        f"{'source':<12}{'submit/s':>10}{'reply/s':>10}{'p50ms':>8}"
        f"{'p95ms':>8}{'p99ms':>8}{'queue':>7}{'sheds':>7}{'idle':>6}"
    ]
    for src in sorted(latest):
        window = latest[src]
        rate = window.get("rate", {})
        ctr = window.get("ctr", {})
        gauges = window.get("g", {})
        hist = window.get("h", {}).get("latency_ms")

        def _num(value, fmt="{:.1f}"):
            return "-" if value is None else fmt.format(value)

        lines.append(
            f"{src:<12}"
            f"{_num(rate.get('submitted')):>10}"
            f"{_num(rate.get('replied')):>10}"
            f"{_num(hist and hist.get('p50'), '{:.0f}'):>8}"
            f"{_num(hist and hist.get('p95'), '{:.0f}'):>8}"
            f"{_num(hist and hist.get('p99'), '{:.0f}'):>8}"
            f"{_num(gauges.get('queue_depth'), '{:.0f}'):>7}"
            f"{_num(ctr.get('shed_submissions'), '{:.0f}'):>7}"
            f"{_num(gauges.get('device_idle_frac'), '{:.2f}'):>6}"
        )
    errors = [
        f"! {src}: {window['err']}"
        for src, window in sorted(latest.items())
        if "err" in window
    ]
    return "\n".join(lines + errors)


def cmd_watch(args) -> int:
    """Live terminal view of a cluster's telemetry: re-render the latest
    window per source every ``--interval`` seconds (``--once`` renders a
    single frame — the CI spelling)."""
    while True:
        latest = _watch_sources(args.target)
        frame = _render_watch(latest)
        if args.once:
            print(frame)
            return 0 if latest else 1
        # full-frame repaint (clear + home), like watch(1)
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


def cmd_critpath(args) -> int:
    """Cross-process critical-path attribution: stitch spans causally
    over the message edges, resolve clock offsets, and print the p99
    blame — which stage, which peer, which dependency."""
    from fantoch_tpu.observability.critpath import critpath_report

    report = critpath_report(
        _load(args.trace), percentile=args.percentile,
        exemplars=args.exemplars,
    )
    if args.json:
        print(json.dumps(report, sort_keys=True))
        return 0
    print(
        f"spans: {report['spans']}  stitched: {report['stitched']} "
        f"({report['stitch_rate'] * 100:.1f}%)  clock: {report['clock']}"
    )
    if report["telescoping_violations"]:
        print(f"TELESCOPING VIOLATIONS: {report['telescoping_violations']}")
    p99 = report["p99"]
    print(
        f"p99 cohort: {p99['count']} span(s) >= "
        f"{p99['threshold_us'] / 1000:.2f}ms"
        + (
            f"; dominant stage {p99['dominant_stage']}"
            if p99["dominant_stage"]
            else ""
        )
    )
    print(f"{'stage':<22}{'all mean':>12}{'p99 mean':>12}")
    all_means = report["stage_means_us"]
    for name in sorted(
        set(all_means) | set(p99["stage_means_us"]),
        key=lambda n: -p99["stage_means_us"].get(n, 0),
    ):
        print(
            f"{name:<22}"
            f"{all_means.get(name, 0) / 1000:>11.2f}m"
            f"{p99['stage_means_us'].get(name, 0) / 1000:>11.2f}m"
        )
    for label, table in (
        ("quorum blame (all)", report["quorum_blame"]),
        ("quorum blame (p99)", report["p99_quorum_blame"]),
    ):
        for pid, row in sorted(
            table.items(), key=lambda kv: -kv[1]["count"]
        ):
            print(
                f"{label}: p{pid} blocking {row['count']}x  "
                f"mean wait {row['mean_wait_us'] / 1000:.2f}ms "
                f"(net {row['mean_net_us'] / 1000:.2f}ms, "
                f"remote {row['mean_remote_us'] / 1000:.2f}ms)"
            )
    for label, row in (
        ("ingest-batching (all)", report["ingest_batching"]),
        ("ingest-batching (p99)", report["p99_ingest_batching"]),
    ):
        if row["spans"]:
            print(
                f"{label}: {row['spans']} span(s)  "
                f"mean hold {row['mean_us'] / 1000:.2f}ms  "
                f"max {row['max_us'] / 1000:.2f}ms"
            )
    for row in report["peers"]:
        print(
            f"peer skew: p{row['pid']} -> p{row['peer']} offset "
            f"{row['offset_us']}us (rtt {row['rtt_us']}us)"
        )
    if report["recovered_spans"]:
        print(f"recovered spans: {report['recovered_spans']}")
    for vector in report["exemplars"]:
        stages = "  ".join(
            f"{name} {us / 1000:.2f}m"
            for name, us in sorted(
                vector["stages"].items(), key=lambda kv: -kv[1]
            )
        )
        quorum = vector["blame"].get("quorum")
        blamed = f" [quorum p{quorum['pid']}]" if quorum else ""
        print(
            f"exemplar rifl {vector['rifl'][0]}.{vector['rifl'][1]} "
            f"total {vector['total_us'] / 1000:.2f}ms{blamed}: {stages}"
        )
    device = report.get("device")
    if device:
        _print_overlap(device)
    return 0


def cmd_to_perfetto(args) -> int:
    from fantoch_tpu.observability.perfetto import write_perfetto

    count = write_perfetto(_load(args.trace), args.output)
    print(f"wrote {count} trace events to {args.output}")
    return 0


def cmd_diff(args) -> int:
    from fantoch_tpu.observability.report import diff_events, diff_stages
    from fantoch_tpu.observability.tracer import read_trace

    if args.stages:
        # tolerance diff of assembled stage latencies: the comparison
        # that works for wall-clock run-layer traces, where byte
        # identity can never hold
        verdict = diff_stages(
            read_trace(args.a), read_trace(args.b),
            tol_frac=args.tol_frac, tol_abs_us=args.tol_abs_us,
        )
        for line in verdict["mismatches"]:
            print(line)
        for side, rifls in (("a", verdict["only_a"]), ("b", verdict["only_b"])):
            if rifls:
                print(f"spans only in {side}: {rifls[:10]}")
        if not verdict["mismatches"] and not verdict["only_a"] and not verdict["only_b"]:
            print(
                f"stage latencies agree within tolerance "
                f"({verdict['matched']} matched spans)"
            )
            return 0
        return 1
    mismatches = diff_events(read_trace(args.a), read_trace(args.b))
    for line in mismatches:
        print(line)
    if not mismatches:
        print("traces identical")
        return 0
    return 1


def cmd_curves(args) -> int:
    """Capacity/SLO report over a scenario curves document: the knee
    table (per curve: points, detected saturation knee, p99 at the knee)
    and every per-cell SLO verdict (typed pass/fail, targets from the
    spec's slo block).  Exit 1 when any verdict fails — the CI shape."""
    import json as _json
    import os

    from fantoch_tpu.plot.db import load_curves

    path = args.curves
    if os.path.isdir(path):
        path = os.path.join(path, "curves.json")
    doc = load_curves(path)
    if args.json:
        print(_json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(f"scenario {doc['scenario']} ({doc['timeline']} timeline, "
              f"seed {doc['seed']})")
        header = (
            f"{'curve':<24} {'points':>6} {'knee offered/s':>14} "
            f"{'knee goodput/s':>14} {'p99@knee ms':>12}"
        )
        print(header)
        for curve in doc["curves"]:
            label = f"{curve['protocol']} n={curve['n']} f={curve['f']}"
            knee = curve.get("knee")
            if knee is None:
                print(f"{label:<24} {len(curve['points']):>6} "
                      f"{'unsaturated':>14} {'-':>14} {'-':>12}")
                continue
            offered = knee["offered_cmds_per_s"]
            print(
                f"{label:<24} {len(curve['points']):>6} "
                f"{offered if offered is not None else '-':>14} "
                f"{knee['goodput_cmds_per_s']:>14} "
                f"{knee['p99_ms'] if knee['p99_ms'] is not None else '-':>12}"
            )
    failed = 0
    checked = 0
    for curve in doc["curves"]:
        for verdict in curve.get("slo", []):
            if not verdict["checks"]:
                continue
            checked += 1
            status = "PASS" if verdict["pass"] else "FAIL"
            if not verdict["pass"]:
                failed += 1
            if not args.json:
                details = ", ".join(
                    f"{name} {check['actual']} vs {check['target']} "
                    f"{'ok' if check['pass'] else 'VIOLATED'}"
                    for name, check in sorted(verdict["checks"].items())
                )
                print(f"  slo {status} {verdict['cell']}: {details}")
    if not args.json and checked == 0:
        print("  (no SLO declared in the spec)")
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="obs", description="dot-lifecycle trace tooling"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summarize", help="per-stage latency breakdown")
    p.add_argument("trace", nargs="+", help="JSONL span log(s)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.set_defaults(fn=cmd_summarize)

    p = sub.add_parser("scrape", help="fetch /metrics exposition endpoint(s)")
    p.add_argument("target", nargs="+",
                   help="endpoint (host:port or full URL)")
    p.add_argument("--json", action="store_true",
                   help="parse the exposition into JSON per target")
    p.set_defaults(fn=cmd_scrape)

    p = sub.add_parser(
        "watch", help="live terminal view of telemetry series/endpoints"
    )
    p.add_argument("target", nargs="+",
                   help="series file, obs dir, or endpoint (host:port)")
    p.add_argument("--interval", type=float, default=1.0, metavar="S")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (CI smoke)")
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser(
        "critpath",
        help="cross-process critical-path attribution (p99 blame)",
    )
    p.add_argument("trace", nargs="+",
                   help="JSONL span log(s) and/or flight dump(s)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report")
    p.add_argument("--percentile", type=float, default=0.99,
                   help="tail cohort threshold (default 0.99)")
    p.add_argument("--exemplars", type=int, default=3,
                   help="worst spans printed with full vectors")
    p.set_defaults(fn=cmd_critpath)

    p = sub.add_parser("to-perfetto", help="convert to trace-event JSON")
    p.add_argument("trace", nargs="+", help="JSONL span log(s)")
    p.add_argument("-o", "--output", required=True, help="output .json path")
    p.set_defaults(fn=cmd_to_perfetto)

    p = sub.add_parser("diff", help="structural diff of two span logs")
    p.add_argument("a")
    p.add_argument("b")
    p.add_argument("--stages", action="store_true",
                   help="tolerance diff of assembled span stage "
                   "latencies (works for wall-clock traces from two "
                   "different runs; the default byte diff never can)")
    p.add_argument("--tol-frac", type=float, default=0.5,
                   help="relative tolerance per segment (default 0.5)")
    p.add_argument("--tol-abs-us", type=int, default=20_000,
                   help="absolute tolerance per segment (default 20ms)")
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "curves",
        help="scenario knee table + per-cell SLO verdicts "
        "(exp/scenarios.py curves.json)",
    )
    p.add_argument("curves",
                   help="curves.json path or a scenario output dir")
    p.add_argument("--json", action="store_true",
                   help="print the raw curves document")
    p.set_defaults(fn=cmd_curves)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
