"""Chaos-fuzzing CLI: seeded sweeps and byte-identical repro replay.

    # sweep seeded cases across the full protocol x nemesis matrix
    # (all five protocols, crash AND restart classes; exit 1 whenever
    # ANY case files a repro artifact — each finding is shrunk, written
    # as JSON, and named in its failure line)
    python -m fantoch_tpu.bin.fuzz run --seed 0 --cases 50 --out-dir repros/

    # replay a repro artifact byte-identically (exit 0 iff the recorded
    # verdict digest reproduces: same plan, same trace, same violations)
    python -m fantoch_tpu.bin.fuzz repro repros/fuzz-000031.json

``run`` honors ``FANTOCH_FUZZ_BUDGET_S`` (or ``--budget-s``) as a wall
budget for longer soak runs: the sweep keeps drawing cases past
``--cases`` until the budget elapses.  ``make fuzz-smoke`` drives the
same machinery with a fixed seed set and asserts auditor-clean runs per
protocol (scripts/fuzz_smoke.py).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def cmd_run(args) -> int:
    from fantoch_tpu.sim.fuzz import (
        PROTOCOL_SPECS,
        FaultPlanFuzzer,
        repro_artifact,
        run_case,
        shrink_case,
        write_repro,
    )

    budget_s = args.budget_s
    if budget_s is None:
        env = os.environ.get("FANTOCH_FUZZ_BUDGET_S")
        budget_s = float(env) if env else None
    protocols = args.protocols.split(",") if args.protocols else None
    if protocols:
        unknown = set(protocols) - set(PROTOCOL_SPECS)
        assert not unknown, f"unknown protocols {sorted(unknown)}"
    fuzzer = FaultPlanFuzzer(seed=args.seed)
    started = time.monotonic()
    tallies = {"ok": 0, "violation": 0, "stall": 0, "incomplete": 0}
    clean_per_protocol: dict = {}
    findings = []
    index = 0
    while True:
        past_cases = index >= args.cases
        past_budget = budget_s is not None and time.monotonic() - started >= budget_s
        # no budget: stop at --cases; with one: the budget is the stop
        if (budget_s is None and past_cases) or past_budget:
            break
        protocol = protocols[index % len(protocols)] if protocols else None
        case = fuzzer.case(index, protocol=protocol)
        result = run_case(case)
        tallies[result.verdict] += 1
        if result.ok:
            clean_per_protocol[case.protocol] = (
                clean_per_protocol.get(case.protocol, 0) + 1
            )
        elif result.verdict == "violation":
            print(
                f"VIOLATION at case {index} ({case.protocol} n={case.n} "
                f"f={case.f}): {result.violations[:1]}"
            )
            shrunk, runs = shrink_case(case)
            os.makedirs(args.out_dir, exist_ok=True)
            # the shrunk finding's confirmation run records its own
            # black box: flight-recorder dumps next to the artifact,
            # attached via the artifact's "flight" field
            shrunk_result = run_case(
                shrunk,
                flight_dir=os.path.join(
                    args.out_dir, f"fuzz-{index:06d}-flight"
                ),
            )
            artifact = repro_artifact(shrunk_result, shrink_runs=runs)
            path = os.path.join(args.out_dir, f"fuzz-{index:06d}.json")
            write_repro(path, artifact)
            findings.append(path)
            print(f"  shrunk in {runs} runs -> {path}")
            for flight_path in shrunk_result.flight:
                print(f"  flight recorder -> {flight_path}")
        index += 1
    elapsed = time.monotonic() - started
    print(
        f"{index} cases in {elapsed:.1f}s: "
        + "  ".join(f"{k}={v}" for k, v in tallies.items())
    )
    print(
        "clean runs per protocol: "
        + ", ".join(f"{p}={c}" for p, c in sorted(clean_per_protocol.items()))
    )
    if findings:
        # any filed artifact fails the sweep — no protocol is exempt
        # (the Caesar filed-not-fixed carve-out died with PR 12), and
        # every failure line names its artifact so the repro is one
        # copy-paste away
        for path in findings:
            print(f"FAILED: repro artifact {path}")
        return 1
    return 0


def cmd_repro(args) -> int:
    from fantoch_tpu.sim.fuzz import load_repro, replay_repro

    artifact = load_repro(args.file)
    result, identical = replay_repro(artifact)
    print(f"recorded verdict: {artifact['verdict']}  replay: {result.verdict}")
    for violation in result.violations:
        print(f"  {violation}")
    if artifact.get("issue"):
        print(f"issue: {artifact['issue']}")
    if identical:
        print("byte-identical: plan/trace/verdict digests match the artifact")
        return 0
    print("MISMATCH: replay diverged from the recorded digests")
    print(f"  recorded verdict_digest {artifact['verdict_digest']}")
    print(f"  replayed verdict_digest {result.verdict_digest}")
    return 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fuzz", description="chaos fuzzing over the deterministic sim"
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("run", help="seeded fuzz sweep")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--cases", type=int, default=50)
    p.add_argument(
        "--budget-s", type=float, default=None,
        help="wall budget; keeps sweeping past --cases "
        "(default: FANTOCH_FUZZ_BUDGET_S)",
    )
    p.add_argument(
        "--protocols", default=None,
        help="comma-separated subset (default: all, sampled)",
    )
    p.add_argument("--out-dir", default="fuzz-repros")
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser("repro", help="replay a JSON repro artifact")
    p.add_argument("file")
    p.set_defaults(fn=cmd_repro)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
