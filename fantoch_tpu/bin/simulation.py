"""Simulation sweep: deterministic protocol runs over a real planet.

Reference: fantoch_ps/src/bin/simulation.rs:47-584 — sweep protocols and
client counts over the AWS planet, reporting per-region latency stats.
(The reference parallelizes with rayon; sweeps here run sequentially —
each sim is already a tight single-threaded event loop.)

    python -m fantoch_tpu.bin.simulation --protocol newt -n 5 -f 1 \\
        --clients 1,10 --conflict-rate 50
"""

from __future__ import annotations

import argparse
import json


def main(argv=None) -> None:
    from fantoch_tpu.bin.common import force_platform_from_env

    force_platform_from_env()
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.simulation", description=__doc__
    )
    parser.add_argument("--protocol", required=True)
    parser.add_argument("--processes", "-n", type=int, required=True)
    parser.add_argument("--faults", "-f", type=int, required=True)
    parser.add_argument("--clients", default="1",
                        help="comma list of clients-per-region to sweep")
    parser.add_argument("--conflict-rate", type=int, default=50)
    parser.add_argument("--keys-per-command", type=int, default=1)
    parser.add_argument("--commands-per-client", type=int, default=50)
    parser.add_argument("--dataset", choices=["aws", "gcp"], default="aws")
    parser.add_argument("--regions", default=None,
                        help="comma list of region names (default: first n)")
    parser.add_argument("--newt-tiny-quorums", action="store_true")
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args(argv)

    from fantoch_tpu.bin.common import protocol_by_name
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.core.planet import Planet, Region
    from fantoch_tpu.sim.runner import Runner

    protocol_cls = protocol_by_name(args.protocol)
    planet = Planet.new(args.dataset)
    if args.regions:
        regions = [Region(name) for name in args.regions.split(",")]
    else:
        regions = sorted(planet.regions())[: args.processes]
    assert len(regions) == args.processes, "one region per process"

    config = Config(
        n=args.processes,
        f=args.faults,
        gc_interval_ms=100,
        newt_tiny_quorums=args.newt_tiny_quorums,
    )

    for clients in [int(c) for c in args.clients.split(",")]:
        workload = Workload(
            shard_count=1,
            key_gen=ConflictRateKeyGen(args.conflict_rate),
            keys_per_command=args.keys_per_command,
            commands_per_client=args.commands_per_client,
            payload_size=1,
        )
        runner = Runner(
            protocol_cls,
            planet,
            config,
            workload,
            clients,
            process_regions=list(regions),
            client_regions=list(regions),
            seed=args.seed,
        )
        _metrics, _monitors, latencies = runner.run(extra_sim_time_ms=10_000)
        stats = {
            str(region): {
                "issued": issued,
                "mean_ms": round(hist.mean(), 1),
                "p99_ms": hist.percentile(0.99),
            }
            for region, (issued, hist) in sorted(
                latencies.items(), key=lambda kv: str(kv[0])
            )
        }
        print(
            json.dumps(
                {
                    "protocol": args.protocol,
                    "n": args.processes,
                    "f": args.faults,
                    "clients_per_region": clients,
                    "latency": stats,
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
