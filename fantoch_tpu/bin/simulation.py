"""Simulation sweep: deterministic protocol runs over a real planet.

Reference: fantoch_ps/src/bin/simulation.rs:47-584 — sweep protocols and
client counts over the AWS planet, reporting per-region latency stats.
The reference parallelizes with rayon; ``--parallel N`` here fans sweep
points out over worker processes (each sim is a tight single-threaded
event loop, so process-level parallelism is the right grain).

    python -m fantoch_tpu.bin.simulation --protocol newt -n 5 -f 1 \\
        --clients 1,10 --conflict-rate 50 --parallel 4
"""

from __future__ import annotations

import argparse
import json


def _run_point(params: dict) -> str:
    """One sweep point -> its JSON result line.  Module-level and fed by a
    plain dict so ProcessPoolExecutor workers can pickle the call.

    Always CPU: a simulation is a host-side deterministic event loop, and
    concurrent workers must never race to initialize the one TPU backend
    (hostenv.py: backend init can block indefinitely)."""
    from fantoch_tpu.hostenv import force_cpu_platform

    force_cpu_platform()

    from fantoch_tpu.bin.common import protocol_by_name
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core import Config
    from fantoch_tpu.core.planet import Planet, Region
    from fantoch_tpu.sim.runner import Runner

    protocol_cls = protocol_by_name(params["protocol"])
    planet = Planet.new(params["dataset"])
    if params["regions"]:
        regions = [Region(name) for name in params["regions"]]
    else:
        regions = sorted(planet.regions())[: params["n"]]
    assert len(regions) == params["n"], "one region per process"

    config = Config(
        n=params["n"],
        f=params["f"],
        gc_interval_ms=100,
        newt_tiny_quorums=params["tiny_quorums"],
        # Newt liveness requires flushing detached votes (the reference's
        # newt_config! macro always sets it, fantoch_ps/src/protocol/
        # mod.rs:65); harmless for the other protocols
        newt_detached_send_interval_ms=100,
        # leader-based protocols need one (the reference's config! macro
        # sets leader = 1 for fpaxos sims, fantoch_ps/src/protocol/
        # mod.rs:698-716); ignored by the leaderless protocols
        leader=params["leader"],
    )
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(params["conflict_rate"]),
        keys_per_command=params["keys_per_command"],
        commands_per_client=params["commands_per_client"],
        payload_size=1,
    )
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        params["clients"],
        process_regions=list(regions),
        client_regions=list(regions),
        seed=params["seed"],
    )
    _metrics, _monitors, latencies = runner.run(extra_sim_time_ms=10_000)
    stats = {
        str(region): {
            "issued": issued,
            "mean_ms": round(hist.mean(), 1),
            "p99_ms": hist.percentile(0.99),
        }
        for region, (issued, hist) in sorted(
            latencies.items(), key=lambda kv: str(kv[0])
        )
    }
    return json.dumps(
        {
            "protocol": params["protocol"],
            "n": params["n"],
            "f": params["f"],
            "clients_per_region": params["clients"],
            "latency": stats,
        }
    )


def main(argv=None) -> None:
    from fantoch_tpu.bin.common import force_platform_from_env

    force_platform_from_env(touches_default_backend=False)
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.simulation", description=__doc__
    )
    parser.add_argument("--protocol", required=True)
    parser.add_argument("--processes", "-n", type=int, required=True)
    parser.add_argument("--faults", "-f", type=int, required=True)
    parser.add_argument("--clients", default="1",
                        help="comma list of clients-per-region to sweep")
    parser.add_argument("--conflict-rate", type=int, default=50)
    parser.add_argument("--keys-per-command", type=int, default=1)
    parser.add_argument("--commands-per-client", type=int, default=50)
    parser.add_argument("--dataset", choices=["aws", "gcp"], default="aws")
    parser.add_argument("--regions", default=None,
                        help="comma list of region names (default: first n)")
    parser.add_argument("--newt-tiny-quorums", action="store_true")
    parser.add_argument("--leader", type=int, default=1,
                        help="initial leader process id (leader-based protocols)")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--parallel", type=int, default=1,
                        help="worker processes for the sweep (rayon analog)")
    args = parser.parse_args(argv)
    if not 1 <= args.leader <= args.processes:
        parser.error(
            f"--leader {args.leader} out of range: process ids are "
            f"1..{args.processes}"
        )

    points = [
        {
            "protocol": args.protocol,
            "n": args.processes,
            "f": args.faults,
            "clients": clients,
            "conflict_rate": args.conflict_rate,
            "keys_per_command": args.keys_per_command,
            "commands_per_client": args.commands_per_client,
            "dataset": args.dataset,
            "regions": args.regions.split(",") if args.regions else None,
            "tiny_quorums": args.newt_tiny_quorums,
            "leader": args.leader,
            "seed": args.seed,
        }
        for clients in [int(c) for c in args.clients.split(",")]
    ]

    if args.parallel > 1 and len(points) > 1:
        import concurrent.futures
        import multiprocessing
        import os

        # a JAX_PLATFORMS env var hangs worker interpreter start under the
        # sitecustomize TPU hook (hostenv.py postmortem) — and main() may
        # have just set it in-process via force_platform_from_env; workers
        # force CPU in-Python instead (_run_point)
        os.environ.pop("JAX_PLATFORMS", None)
        # spawn: workers must not inherit an initialized jax backend
        ctx = multiprocessing.get_context("spawn")
        with concurrent.futures.ProcessPoolExecutor(
            max_workers=min(args.parallel, len(points)), mp_context=ctx
        ) as pool:
            for line in pool.map(_run_point, points):
                print(line, flush=True)
    else:
        for point in points:
            print(_run_point(point), flush=True)


if __name__ == "__main__":
    main()
