"""Experiment driver CLI: run one experiment or a client sweep end to end.

Reference: fantoch_exp/src/bin/main.rs — the experiment harness entry
that launches a cluster, runs protocol + client binaries with generated
flags, and collects logs/metrics/profiles.  Here the testbed is
localhost subprocesses by default, or an SSH host list (the baremetal.rs
analog); ``--run-mode`` selects the Release/Flamegraph/Heaptrack analog
(release / cprofile / memory).

    python -m fantoch_tpu.bin.exp --protocol epaxos -n 3 -f 1 \\
        --clients-sweep 1,2,4 --commands-per-client 50 \\
        --output-dir ./exp_out --run-mode cprofile

    python -m fantoch_tpu.bin.exp --protocol newt -n 3 -f 1 \\
        --output-dir ./exp_out --hosts h1,h2,h3   # SSH testbed

Each experiment directory gets a manifest.json (config, pulled
artifacts, outcome) — the input `fantoch_tpu.plot.ResultsDB` indexes.
"""

from __future__ import annotations

import argparse
import json


def main(argv=None) -> None:
    from fantoch_tpu.bin.common import force_platform_from_env

    force_platform_from_env(touches_default_backend=False)
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.exp", description=__doc__
    )
    parser.add_argument("--protocol", required=True)
    parser.add_argument("--processes", "-n", type=int, required=True)
    parser.add_argument("--faults", "-f", type=int, required=True)
    parser.add_argument("--shard-count", type=int, default=1)
    clients_group = parser.add_mutually_exclusive_group()
    clients_group.add_argument("--clients", type=int, default=1,
                               help="clients per process (single experiment)")
    clients_group.add_argument("--clients-sweep", default=None,
                               help="comma list of client counts: one "
                               "experiment per point (the "
                               "throughput-latency curve shape)")
    parser.add_argument("--commands-per-client", type=int, default=100)
    parser.add_argument("--conflict-rate", type=int, default=50)
    parser.add_argument("--keys-per-command", type=int, default=1)
    parser.add_argument("--key-gen", choices=["conflict_rate", "zipf"],
                        default="conflict_rate")
    parser.add_argument("--zipf-coefficient", type=float, default=1.0)
    parser.add_argument("--batched-graph-executor", action="store_true")
    parser.add_argument("--device-step", action="store_true",
                        help="run the experiment against one --device-step "
                        "server (the TPU serving path) instead of an "
                        "n-process TCP mesh")
    parser.add_argument("--device-batch", type=int, default=256)
    parser.add_argument("--run-mode",
                        choices=["release", "cprofile", "memory"],
                        default="release")
    parser.add_argument("--output-dir", required=True)
    parser.add_argument("--hosts", default=None,
                        help="comma list of SSH hosts (default: localhost "
                        "subprocesses)")
    parser.add_argument("--client-timeout", type=int, default=600,
                        metavar="S")
    args = parser.parse_args(argv)

    from fantoch_tpu.exp import ExperimentConfig, run_experiment, run_sweep

    base = ExperimentConfig(
        protocol=args.protocol,
        n=args.processes,
        f=args.faults,
        shard_count=args.shard_count,
        clients_per_process=args.clients,
        commands_per_client=args.commands_per_client,
        key_gen=args.key_gen,
        conflict_rate=args.conflict_rate,
        zipf_coefficient=args.zipf_coefficient,
        keys_per_command=args.keys_per_command,
        batched_graph_executor=args.batched_graph_executor,
        device_step=args.device_step,
        device_batch=args.device_batch,
    )
    testbed = "localhost"
    if args.hosts:
        from fantoch_tpu.exp.testbed import HostsTestbed

        testbed = HostsTestbed(args.hosts.split(","))

    if args.clients_sweep:
        sweep = [int(c) for c in args.clients_sweep.split(",")]
        manifests = run_sweep(
            base, args.output_dir, sweep, testbed=testbed,
            client_timeout_s=args.client_timeout, run_mode=args.run_mode,
        )
    else:
        manifests = [
            run_experiment(
                base, args.output_dir, testbed=testbed,
                client_timeout_s=args.client_timeout,
                run_mode=args.run_mode,
            )
        ]
    for manifest in manifests:
        print(json.dumps({
            "name": manifest["name"],
            "run_mode": manifest["run_mode"],
            "outcome": manifest["outcome"],
        }), flush=True)


if __name__ == "__main__":
    main()
