"""Shared CLI plumbing: protocol registry, config flags, platform forcing.

Reference: fantoch_ps/src/bin/common/protocol.rs:126-368 (the full server
flag set) and common/mod.rs.  The TPU platform is forced *in-Python*
before the first jax import (a JAX_PLATFORMS env var hangs interpreter
start under this rig's TPU hook — see bench.py's postmortem), via the
FANTOCH_PLATFORM environment variable.
"""

from __future__ import annotations

import argparse
import os
from typing import Optional, Tuple


def force_platform_from_env(touches_default_backend: bool = True) -> None:
    """FANTOCH_PLATFORM=cpu forces the CPU backend before jax loads.

    ``touches_default_backend=False`` for entrypoints that always force
    CPU themselves later (the simulation sweep's workers): no breadcrumb,
    it would warn about a backend the run never touches."""
    if os.environ.get("FANTOCH_PLATFORM") == "cpu":
        from fantoch_tpu.hostenv import force_cpu_platform

        force_cpu_platform()
    elif touches_default_backend:
        import sys

        # backend init on the default (TPU) platform can block
        # indefinitely when the chip tunnel is down (hostenv.py
        # postmortem) — leave a breadcrumb so a silent hang is
        # diagnosable and escapable
        print(
            "# jax backend initializes on first use (default platform); "
            "if this hangs, the TPU tunnel is unreachable — set "
            "FANTOCH_PLATFORM=cpu to force the CPU backend",
            file=sys.stderr,
        )
    # the persistent XLA compile cache (the same in-repo dir bench.py and
    # tests/conftest.py use — after the platform forcing above): a CLI
    # server's first device-plane dispatch otherwise pays a full cold
    # compile INSIDE the serving loop — on a 1-core rig the graph-plane
    # step compiles for minutes, starving the heartbeat task until peers
    # declare the process dead (quorum suicide).  Cache hits load in
    # well under a second; the helper swallows failures (optimization
    # only)
    from fantoch_tpu.hostenv import enable_compile_cache

    enable_compile_cache()


def protocol_by_name(name: str):
    from fantoch_tpu.protocol import Atlas, Basic, Caesar, EPaxos, FPaxos, Newt

    registry = {
        "basic": Basic,
        "epaxos": EPaxos,
        "atlas": Atlas,
        "newt": Newt,
        "caesar": Caesar,
        "fpaxos": FPaxos,
    }
    if name not in registry:
        raise SystemExit(f"unknown protocol {name!r}; one of {sorted(registry)}")
    return registry[name]


def add_config_flags(parser: argparse.ArgumentParser) -> None:
    """The Config-backed flags (common/protocol.rs:126-368)."""
    parser.add_argument("--processes", "-n", type=int, required=True, help="replicas per shard")
    parser.add_argument("--faults", "-f", type=int, required=True)
    parser.add_argument("--shard-count", type=int, default=1)
    parser.add_argument("--execute-at-commit", action="store_true")
    parser.add_argument("--executor-executed-notification-interval", type=int, default=50, metavar="MS")
    parser.add_argument("--executor-cleanup-interval", type=int, default=5, metavar="MS")
    parser.add_argument("--executor-monitor-execution-order", action="store_true")
    parser.add_argument("--gc-interval", type=int, default=50, metavar="MS")
    parser.add_argument("--leader", type=int, default=None, help="leader process (FPaxos)")
    parser.add_argument(
        "--fpaxos-leader-timeout", type=int, default=None, metavar="MS",
        help="FPaxos leader failover: heartbeat at a quarter of this, "
        "followers elect after ring-staggered silence (also unlocks the "
        "crash-restart rejoin via MSlotSync; requires --gc-interval)",
    )
    parser.add_argument("--newt-tiny-quorums", action="store_true")
    parser.add_argument("--newt-clock-bump-interval", type=int, default=None, metavar="MS")
    parser.add_argument("--newt-detached-send-interval", type=int, default=None, metavar="MS")
    parser.add_argument("--caesar-wait-condition", action="store_true", default=True)
    parser.add_argument("--no-caesar-wait-condition", dest="caesar_wait_condition", action="store_false")
    parser.add_argument("--skip-fast-ack", action="store_true")
    parser.add_argument("--batched-graph-executor", action="store_true",
                        help="order committed commands with the batched device resolver")
    parser.add_argument("--device-pred-plane", action="store_true",
                        help="Caesar resident predecessors plane "
                        "(executor/pred_plane.py): the pending window "
                        "stays on device across batches; commits drain "
                        "as column batches")
    parser.add_argument("--device-graph-plane", action="store_true",
                        default=None,
                        help="EPaxos/Atlas resident graph plane "
                        "(executor/graph/graph_plane.py): the dependency "
                        "backlog stays on device across feeds; requires "
                        "--batched-graph-executor and shard-count 1; "
                        "default FANTOCH_GRAPH_PLANE env, else off")
    parser.add_argument("--graph-kernel-threshold", type=int, default=None,
                        metavar="N",
                        help="backlog size gating exact structure metrics "
                        "and the resident general path in the batched "
                        "graph executor; default "
                        "FANTOCH_GRAPH_KERNEL_THRESHOLD env, else 4096")
    parser.add_argument("--serving-pipeline-depth", type=int, default=None,
                        metavar="K",
                        help="device serving pipeline depth (run/pipeline.py): "
                        "dispatched-but-undrained rounds kept in flight; "
                        "default FANTOCH_SERVING_PIPELINE_DEPTH env, else 1")
    parser.add_argument("--ingest-deadline", type=float, default=None,
                        metavar="MS", dest="ingest_deadline_ms",
                        help="adaptive ingest batching deadline budget "
                        "(run/ingest.py): a queued submission waits at most "
                        "this long for its round to fill; default "
                        "FANTOCH_INGEST_DEADLINE_MS env, else 2.0; "
                        "0 disables batching")
    parser.add_argument("--ingest-target", type=int, default=None,
                        metavar="N", dest="ingest_target",
                        help="fixed ingest size target (rows that release "
                        "a round), overriding the EWMA-adaptive target; "
                        "default FANTOCH_INGEST_TARGET env, else adaptive")
    parser.add_argument("--serving-chain-max", type=int, default=None,
                        metavar="S", dest="serving_chain_max",
                        help="ceiling on the auto-tuned serving chain "
                        "length (rounds fused per device dispatch); "
                        "default FANTOCH_SERVING_CHAIN_MAX env, else 8; "
                        "1 disables chaining")
    parser.add_argument("--wal-sync", default=None,
                        choices=("always", "interval", "never"),
                        help="durable command-log fsync policy (run/wal.py); "
                        "default FANTOCH_WAL_SYNC env, else 'interval'; only "
                        "consulted when the server runs with --wal-dir")
    parser.add_argument("--queue-capacity", type=int, default=None,
                        metavar="N",
                        help="high watermark of the run-layer bounded queues "
                        "(run/backpressure.py): readers pause past it; "
                        "default 8192, 0 = unbounded legacy")
    parser.add_argument("--admission-limit", type=int, default=None,
                        metavar="N",
                        help="client-edge admission depth: past it new "
                        "submissions are shed with a typed Overloaded "
                        "reply + retry-after hint; omit to disable shedding")
    parser.add_argument("--overload-retry-after", type=int, default=100,
                        metavar="MS",
                        help="base retry-after hint on Overloaded replies")
    parser.add_argument("--link-unacked-cap", type=int, default=None,
                        metavar="N",
                        help="cap on a peer link's unacked resend window "
                        "(run/links.py): past it the link is declared lost "
                        "via the typed path; default 32768, 0 = uncapped")
    parser.add_argument("--telemetry-interval", type=int, default=None,
                        metavar="MS",
                        help="live-telemetry window cadence "
                        "(observability/timeseries.py): one knob for the "
                        "windowed series emit AND the legacy metrics "
                        "snapshot; default = the runtime's "
                        "--metrics-interval (run) or 1000ms (sim)")
    parser.add_argument("--execution-digests", action="store_true",
                        help="consistency-audit plane (core/audit.py): "
                        "per-key hash chains over executed writes, "
                        "exchanged on the heartbeat path — a forked "
                        "replica surfaces a typed DivergenceError naming "
                        "the first diverging key+command")
    parser.add_argument("--audit-commits", action="store_true",
                        help="record every commit decision (dot/slot -> "
                        "(rifl, value), surviving GC) so divergence "
                        "errors resolve dots and the auditor can check "
                        "commit-value agreement (audit/test only: the "
                        "log grows with the run)")
    parser.add_argument("--trace", type=float, default=0.0, metavar="RATE",
                        dest="trace_sample_rate",
                        help="per-dot lifecycle tracing sample rate "
                        "(0.0-1.0; Config.trace_sample_rate).  Servers "
                        "also need --trace-file; 1.0 stitches every span "
                        "for `bin/obs.py critpath`")
    parser.add_argument("--flight-recorder", action="store_true",
                        help="failure flight recorder "
                        "(observability/recorder.py): bounded in-memory "
                        "ring of recent UNSAMPLED trace events, dumped as "
                        "flight_p<pid>.json on typed failures, WAL-restart "
                        "boots, and SIGUSR1 (capacity: "
                        "FANTOCH_FLIGHT_EVENTS)")


def config_from_args(args: argparse.Namespace):
    from fantoch_tpu.core import Config

    return Config(
        n=args.processes,
        f=args.faults,
        shard_count=args.shard_count,
        execute_at_commit=args.execute_at_commit,
        executor_executed_notification_interval_ms=args.executor_executed_notification_interval,
        executor_cleanup_interval_ms=args.executor_cleanup_interval,
        executor_monitor_execution_order=args.executor_monitor_execution_order,
        gc_interval_ms=args.gc_interval,
        leader=args.leader,
        fpaxos_leader_timeout_ms=args.fpaxos_leader_timeout,
        newt_tiny_quorums=args.newt_tiny_quorums,
        newt_clock_bump_interval_ms=args.newt_clock_bump_interval,
        newt_detached_send_interval_ms=args.newt_detached_send_interval,
        caesar_wait_condition=args.caesar_wait_condition,
        skip_fast_ack=args.skip_fast_ack,
        batched_graph_executor=args.batched_graph_executor,
        device_graph_plane=args.device_graph_plane,
        graph_kernel_threshold=args.graph_kernel_threshold,
        device_pred_plane=args.device_pred_plane,
        serving_pipeline_depth=args.serving_pipeline_depth,
        ingest_deadline_ms=args.ingest_deadline_ms,
        ingest_target=args.ingest_target,
        serving_chain_max=args.serving_chain_max,
        wal_sync=args.wal_sync,
        queue_capacity=args.queue_capacity,
        admission_limit=args.admission_limit,
        overload_retry_after_ms=args.overload_retry_after,
        link_unacked_cap=args.link_unacked_cap,
        execution_digests=args.execution_digests,
        audit_log_commits=args.audit_commits,
        telemetry_interval_ms=args.telemetry_interval,
        trace_sample_rate=args.trace_sample_rate,
        flight_recorder=args.flight_recorder,
    )


def parse_peer(entry: str) -> Tuple[int, str, int, Optional[int]]:
    """'pid=host:port' or 'pid=host:port:delay_ms' -> (pid, host, port, delay)."""
    pid_s, addr = entry.split("=", 1)
    parts = addr.split(":")
    if len(parts) == 2:
        host, port = parts
        delay = None
    elif len(parts) == 3:
        host, port, delay_s = parts
        delay = int(delay_s)
    else:
        raise SystemExit(f"bad peer address {entry!r} (pid=host:port[:delay_ms])")
    return int(pid_s), host, int(port), delay


def parse_shard_addr(entry: str) -> Tuple[int, str, int]:
    """'shard=host:port' -> (shard, host, port)."""
    shard_s, addr = entry.split("=", 1)
    host, port_s = addr.rsplit(":", 1)
    return int(shard_s), host, int(port_s)


def parse_sorted(entry: str) -> list:
    """'1:0,2:0,3:0' -> [(pid, shard), ...]."""
    out = []
    for item in entry.split(","):
        pid_s, shard_s = item.split(":")
        out.append((int(pid_s), int(shard_s)))
    return out


def parse_id_range(entry: str) -> list:
    """'1-3' or '7' -> [ids]."""
    if "-" in entry:
        lo, hi = entry.split("-")
        return list(range(int(lo), int(hi) + 1))
    return [int(entry)]


def maybe_log_file(path: Optional[str]) -> None:
    if path:
        import logging

        handler = logging.FileHandler(path)
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(message)s"))
        logging.getLogger("fantoch_tpu").addHandler(handler)
        logging.getLogger("fantoch_tpu").setLevel(logging.INFO)
