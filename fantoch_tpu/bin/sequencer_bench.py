"""Sequencer microbenchmark: key-clock proposal throughput.

Reference: fantoch_ps/src/bin/sequencer_bench.rs — measures the key-clock
sequencer (the Newt proposal hot loop) under configurable keys / clients.
Here both implementations are measured: the host ``SequentialKeyClocks``
(per-command Python bumps) and the batched device kernel
``batched_clock_proposal`` (one launch per batch), reporting commands/s
for each.

    python -m fantoch_tpu.bin.sequencer_bench --keys 64 --batch 100000
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> None:
    from fantoch_tpu.bin.common import force_platform_from_env

    force_platform_from_env()
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.sequencer_bench", description=__doc__
    )
    parser.add_argument("--keys", type=int, default=64)
    parser.add_argument("--batch", type=int, default=100_000)
    parser.add_argument("--iters", type=int, default=5)
    parser.add_argument("--host-batch", type=int, default=None,
                        help="commands for the host measurement "
                        "(default: min(batch, 50000))")
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fantoch_tpu.core.command import Command
    from fantoch_tpu.core.ids import Rifl
    from fantoch_tpu.core.kvs import KVOp
    from fantoch_tpu.ops.table_ops import batched_clock_proposal
    from fantoch_tpu.protocol.common.table_clocks import SequentialKeyClocks

    rng = np.random.default_rng(3)
    key = jnp.asarray(rng.integers(0, args.keys, size=args.batch), jnp.int32)
    mins = jnp.zeros((args.batch,), jnp.int32)
    prior = jnp.zeros((args.keys,), jnp.int32)

    # device: one kernel launch per batch
    out = batched_clock_proposal(prior, key, mins)
    jax.block_until_ready(out[0])
    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        out = batched_clock_proposal(out[2], key, mins)
        jax.block_until_ready(out[0])
        times.append(time.perf_counter() - t0)
    device_s = float(np.median(times))

    # host: per-command proposal (the reference's sequencer shape)
    host_batch = args.host_batch or min(args.batch, 50_000)
    clocks = SequentialKeyClocks(1, 0)
    cmds = [
        Command.from_single(
            Rifl(1, i + 1), 0, str(int(k)), KVOp.put("x")
        )
        for i, k in enumerate(np.asarray(key[:host_batch]))
    ]
    t0 = time.perf_counter()
    for cmd in cmds:
        clocks.proposal(cmd, 0)
    host_s = time.perf_counter() - t0

    print(
        json.dumps(
            {
                "keys": args.keys,
                "batch": args.batch,
                "device_cmds_per_s": int(args.batch / device_s),
                "host_batch": host_batch,
                "host_cmds_per_s": int(host_batch / host_s),
                "speedup": round((args.batch / device_s) / (host_batch / host_s), 1),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
