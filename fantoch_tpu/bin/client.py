"""Client binary: drive a workload against a cluster.

Reference: fantoch_ps/src/bin/client.rs:65-172 (clap flag set: id ranges,
per-shard addresses, open-loop interval, workload knobs, metrics file).

Example:
    python -m fantoch_tpu.bin.client --ids 1-4 \\
        --addresses 0=127.0.0.1:8001 \\
        --commands-per-client 100 --conflict-rate 50 --payload-size 16
"""

from __future__ import annotations

import argparse
import asyncio
import json
import pickle

from fantoch_tpu.bin.common import (
    force_platform_from_env,
    maybe_log_file,
    parse_id_range,
    parse_shard_addr,
)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.client", description=__doc__
    )
    parser.add_argument("--ids", required=True, help="client id range, e.g. 1-8")
    parser.add_argument(
        "--addresses",
        required=True,
        help="comma list of shard=host:client_port (one per shard)",
    )
    parser.add_argument("--interval", type=int, default=None, metavar="MS",
                        help="open-loop submit interval; omit for closed loop")
    parser.add_argument("--arrival-rate", type=float, default=None,
                        metavar="PER_S",
                        help="open-loop Poisson arrival rate per client "
                        "(run/backpressure.OpenLoopPacer); mutually "
                        "exclusive with --interval")
    parser.add_argument("--arrival-seed", type=int, default=None,
                        help="seed for the Poisson arrival gaps and the "
                        "overload-retry jitter (reproducible schedules)")
    parser.add_argument("--deadline", type=int, default=None, metavar="MS",
                        help="per-command deadline budget across overload "
                        "retries: once it expires the command is shed, "
                        "not executed late")
    # workload flags (client.rs:100-151)
    parser.add_argument("--key-gen", choices=["conflict_rate", "zipf"],
                        default="conflict_rate")
    parser.add_argument("--conflict-rate", type=int, default=50)
    parser.add_argument("--zipf-coefficient", type=float, default=1.0)
    parser.add_argument("--keys-per-shard", type=int, default=1_000_000)
    parser.add_argument("--keys-per-command", type=int, default=1)
    parser.add_argument("--commands-per-client", type=int, required=True)
    parser.add_argument("--read-only-percentage", type=int, default=0)
    parser.add_argument("--payload-size", type=int, default=0)
    parser.add_argument("--shard-count", type=int, default=None,
                        help="defaults to the number of --addresses entries")
    parser.add_argument("--metrics-file", default=None,
                        help="pickle the per-client latency data here")
    parser.add_argument("--telemetry-file", default=None,
                        help="client-plane windowed telemetry series "
                        "(observability/timeseries.py): submit/reply "
                        "rates, retry/shed tallies, latency windows")
    parser.add_argument("--telemetry-interval", type=int, default=None,
                        metavar="MS", help="telemetry window cadence "
                        "(default 1000)")
    parser.add_argument("--status-frequency", type=int, default=None)
    parser.add_argument("--trace", type=float, default=0.0, metavar="RATE",
                        help="client-plane lifecycle tracing sample rate "
                        "(needs --trace-file): submit/reply span events "
                        "that `bin/obs.py critpath` stitches against the "
                        "servers' logs")
    parser.add_argument("--trace-file", default=None, metavar="PATH",
                        help="client-plane span log (JSONL)")
    parser.add_argument("--log-file", default=None)
    return parser


def workload_from_args(args: argparse.Namespace, shard_count: int):
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.client.key_gen import ZipfKeyGen

    if args.key_gen == "conflict_rate":
        key_gen = ConflictRateKeyGen(args.conflict_rate)
    else:
        key_gen = ZipfKeyGen(args.zipf_coefficient, args.keys_per_shard)
    return Workload(
        shard_count=shard_count,
        key_gen=key_gen,
        keys_per_command=args.keys_per_command,
        commands_per_client=args.commands_per_client,
        read_only_percentage=args.read_only_percentage,
        payload_size=args.payload_size,
    )


async def drive(args: argparse.Namespace) -> None:
    from fantoch_tpu.run.client_runner import run_clients

    shard_addresses = {}
    for entry in args.addresses.split(","):
        shard, host, port = parse_shard_addr(entry)
        shard_addresses[shard] = (host, port)
    shard_count = args.shard_count or len(shard_addresses)
    client_ids = parse_id_range(args.ids)
    workload = workload_from_args(args, shard_count)

    import time

    # client-plane lifecycle tracing: the submit/reply span events the
    # critical-path correlator stitches against the servers' logs
    tracer = None
    if args.trace_file is not None and args.trace > 0:
        from fantoch_tpu.core.timing import RunTime
        from fantoch_tpu.observability.tracer import Tracer

        tracer = Tracer(RunTime(), args.trace_file, args.trace, clock="wall")

    t0 = time.perf_counter()
    try:
        clients = await run_clients(
            client_ids,
            shard_addresses,
            workload,
            open_loop_interval_ms=args.interval,
            arrival_rate_per_s=args.arrival_rate,
            arrival_seed=args.arrival_seed,
            deadline_ms=args.deadline,
            status_frequency=args.status_frequency,
            telemetry_file=args.telemetry_file,
            telemetry_interval_ms=args.telemetry_interval,
            **({"tracer": tracer} if tracer is not None else {}),
        )
    finally:
        if tracer is not None:
            tracer.close()
    elapsed_s = time.perf_counter() - t0

    latencies = []  # ClientData latencies are microseconds (data.py)
    sheds = retries = 0
    for client in clients.values():
        latencies.extend(client.data().latency_data())
        sheds += client.shed_commands
        retries += client.overload_retries
    latencies.sort()
    total = len(latencies)

    def ms(micros):
        return round(micros / 1000.0, 3)

    summary = {
        "clients": len(clients),
        "commands": total,
        # workload wall time measured inside the client (excludes the
        # subprocess's interpreter/JAX startup — the honest throughput base)
        "elapsed_s": round(elapsed_s, 3),
        "throughput_cmds_per_s": round(total / elapsed_s, 1) if elapsed_s else None,
        # overload plane: completed/total is the goodput; sheds are
        # deadline-expired commands the plane refused to execute late
        "shed_commands": sheds,
        "overload_retries": retries,
        "latency_ms": {
            "min": ms(latencies[0]) if total else None,
            "p50": ms(latencies[total // 2]) if total else None,
            "p99": ms(latencies[int(total * 0.99)]) if total else None,
            "max": ms(latencies[-1]) if total else None,
        },
    }
    print(json.dumps(summary), flush=True)

    if args.metrics_file:
        with open(args.metrics_file, "wb") as fh:
            pickle.dump({cid: c.data() for cid, c in clients.items()}, fh)


def main(argv=None) -> None:
    force_platform_from_env()
    args = build_parser().parse_args(argv)
    maybe_log_file(args.log_file)
    asyncio.run(drive(args))


if __name__ == "__main__":
    main()
