"""Analytic: fraction of multi-shard / multi-key commands under zipf.

Reference: fantoch_ps/src/bin/shard_distribution.rs:1-111 — for a given
shard count and zipf coefficient, sample commands and report how many
touch more than one shard (and more than one key), the planner-side input
for deciding whether partial replication pays off.  The scenario
observatory (exp/scenarios.expand) calls :func:`compute_distribution`
directly so every zipf spec's expansion manifest carries its expected
multi-shard fraction.

    python -m fantoch_tpu.bin.shard_distribution --shard-count 4 \\
        --keys-per-command 2 --coefficient 0.7
"""

from __future__ import annotations

import argparse
import json
from typing import Dict


def compute_distribution(
    shard_count: int,
    keys_per_command: int = 2,
    coefficient: float = 1.0,
    keys_per_shard: int = 1_000_000,
    commands: int = 10_000,
    seed: int = 0,
) -> Dict[str, float]:
    """Deterministic for fixed inputs (seeded rng, analytic zipf cdf)."""
    import random

    from fantoch_tpu.client.key_gen import KeyGenState, ZipfKeyGen
    from fantoch_tpu.client.workload import Workload
    from fantoch_tpu.core.ids import IdGen

    workload = Workload(
        shard_count=shard_count,
        key_gen=ZipfKeyGen(coefficient, keys_per_shard),
        keys_per_command=keys_per_command,
        commands_per_client=commands,
        payload_size=0,
    )
    state = KeyGenState(
        workload.key_gen, shard_count, 1, rng=random.Random(seed)
    )
    rifl_gen = IdGen(1)

    multi_shard = 0
    multi_key = 0
    for _ in range(commands):
        nxt = workload.next_cmd(rifl_gen, state)
        assert nxt is not None
        _target, cmd = nxt
        if cmd.multi_shard():
            multi_shard += 1
        if cmd.total_key_count > 1:
            multi_key += 1

    return {
        "shard_count": shard_count,
        "commands": commands,
        "multi_shard_pct": round(100 * multi_shard / commands, 2),
        "multi_key_pct": round(100 * multi_key / commands, 2),
    }


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        prog="fantoch_tpu.bin.shard_distribution", description=__doc__
    )
    parser.add_argument("--shard-count", type=int, required=True)
    parser.add_argument("--keys-per-command", type=int, default=2)
    parser.add_argument("--coefficient", type=float, default=1.0)
    parser.add_argument("--keys-per-shard", type=int, default=1_000_000)
    parser.add_argument("--commands", type=int, default=10_000)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    print(
        json.dumps(
            compute_distribution(
                shard_count=args.shard_count,
                keys_per_command=args.keys_per_command,
                coefficient=args.coefficient,
                keys_per_shard=args.keys_per_shard,
                commands=args.commands,
                seed=args.seed,
            )
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
