"""Compile-wall control: persistent-cache wiring + compiled-program audit.

PR 15's ``jax_compile_ms`` made the wall visible — each new plane
program costs ~50s on the dev rig, and a scenario sweep that perturbs
any shape axis pays it per point.  The defense is two-sided and this
module is the seam for both:

* **Persistent cache** — :func:`ensure_compile_cache` resolves the cache
  directory (``Config.compile_cache_dir`` > ``FANTOCH_COMPILE_CACHE_DIR``
  env > under the obs dir when the caller has one > the repo-adjacent
  ``.jax_cache`` default) and delegates the jax.config flag-setting to
  :func:`fantoch_tpu.hostenv.enable_compile_cache`.  With the cache warm,
  a "compile" is a disk load: ``observability.device`` pairs the cache
  hit/miss monitoring events with the backend-compile duration events so
  ``jax_recompiles`` counts only TRUE compiles (a warm sweep reports 0)
  while ``jax_cache_hits``/``jax_cache_misses`` expose the retrievals.

* **Program-identity audit** — shape canonicalization (pow2 floors on
  capacity, width, chain length, batch) is only proven by counting: the
  hot jitted programs register here (:func:`register_program`) and
  :func:`program_compile_counts` reads each one's compiled-signature
  count (``jit(f)._cache_size()``), so a multi-point sweep can assert
  every plane program compiled exactly ONCE.  A count > 1 names the
  program whose input shapes leaked a non-canonical axis into the
  compiled signature — the regression test
  (tests/test_compile_cache.py) and the bench smoke both assert on it.
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

# the audited hot programs: name -> jitted callable.  Module-level like
# the recompile counters — registration happens at ops-module import, so
# the registry sees every program the process can dispatch.
_programs: Dict[str, Callable] = {}

_enabled_dir: Optional[str] = None


def register_program(name: str, fn: Callable) -> Callable:
    """Register a jitted program for the compiled-identity audit.
    Returns ``fn`` so registration can wrap a definition in place."""
    _programs[name] = fn
    return fn


def program_compile_counts() -> Dict[str, int]:
    """Compiled-signature count per registered program (0 = never
    dispatched).  Uses the jit cache-size introspection; a program whose
    jit object doesn't expose it reports -1 rather than lying."""
    counts: Dict[str, int] = {}
    for name, fn in _programs.items():
        probe = getattr(fn, "_cache_size", None)
        try:
            counts[name] = int(probe()) if probe is not None else -1
        except Exception:  # noqa: BLE001 — introspection only
            counts[name] = -1
    return counts


def compiled_program_identities() -> int:
    """Total distinct compiled signatures across registered programs —
    the bench counter a canonicalized sweep holds constant."""
    return sum(c for c in program_compile_counts().values() if c > 0)


def clear_program_registry() -> None:
    """Test hook: forget registered programs (NOT their jit caches)."""
    _programs.clear()


def resolve_cache_dir(config=None, obs_dir: Optional[str] = None) -> Optional[str]:
    """The cache-dir precedence: explicit config > env > obs-dir default
    > ``None`` (meaning: let hostenv fall back to the repo-adjacent
    ``.jax_cache``)."""
    value = getattr(config, "compile_cache_dir", None) if config else None
    if value:
        return str(value)
    env = os.environ.get("FANTOCH_COMPILE_CACHE_DIR")
    if env:
        return env
    if obs_dir:
        return os.path.join(obs_dir, ".jax_cache")
    return None


def ensure_compile_cache(config=None, obs_dir: Optional[str] = None) -> str:
    """Idempotent persistent-cache enable at the resolved directory;
    returns the directory in effect.  Safe to call from every runner
    seam (device_runner, process_runner, bench, conftest) — only the
    first distinct directory actually flips the jax.config flags."""
    global _enabled_dir
    from fantoch_tpu.hostenv import enable_compile_cache

    cache_dir = resolve_cache_dir(config, obs_dir)
    if cache_dir is None:
        import fantoch_tpu

        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(fantoch_tpu.__file__))),
            ".jax_cache",
        )
    if _enabled_dir == cache_dir:
        return cache_dir
    enable_compile_cache(cache_dir)
    _enabled_dir = cache_dir
    return cache_dir
