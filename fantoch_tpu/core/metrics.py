"""Metrics: exact histograms + aggregated counters.

Reference: fantoch_prof/src/metrics/{mod,histogram,float}.rs — an exact
``Histogram`` over a value->count map with mean/stddev/cov/percentiles, and a
``Metrics`` container holding named histograms and counters with merge
support (used for protocol fast/slow/stable accounting and executor stats).
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, Generic, Hashable, Iterable, List, Optional, Tuple, TypeVar

K = TypeVar("K", bound=Hashable)


class Histogram:
    """Exact histogram over integer values (fantoch_prof/src/metrics/histogram.rs:15-120)."""

    def __init__(self) -> None:
        self._values: Counter = Counter()
        self._count = 0

    def increment(self, value: int, count: int = 1) -> None:
        self._values[value] += count
        self._count += count

    def merge(self, other: "Histogram") -> None:
        self._values.update(other._values)
        self._count += other._count

    @property
    def count(self) -> int:
        return self._count

    def values(self) -> Iterable[Tuple[int, int]]:
        return sorted(self._values.items())

    def mean(self) -> float:
        if self._count == 0:
            return 0.0
        return sum(v * c for v, c in self._values.items()) / self._count

    def stddev(self) -> float:
        if self._count <= 1:
            return 0.0
        mean = self.mean()
        # corrected sample variance (count - 1), matching
        # fantoch_prof/src/metrics/histogram.rs compute_variance
        var = sum(c * (v - mean) ** 2 for v, c in self._values.items()) / (self._count - 1)
        return math.sqrt(var)

    def cov(self) -> float:
        """Coefficient of variation: stddev / mean."""
        mean = self.mean()
        return self.stddev() / mean if mean else 0.0

    def mdtm(self) -> float:
        """Mean distance to mean (mean absolute deviation)."""
        if self._count == 0:
            return 0.0
        mean = self.mean()
        return sum(c * abs(v - mean) for v, c in self._values.items()) / self._count

    def percentile(self, p: float) -> float:
        """p in [0, 1]; nearest-rank percentile over the exact values."""
        assert 0.0 <= p <= 1.0
        if self._count == 0:
            return 0.0
        rank = max(1, math.ceil(p * self._count))
        seen = 0
        for value, count in sorted(self._values.items()):
            seen += count
            if seen >= rank:
                return float(value)
        return float(max(self._values))

    def min(self) -> int:
        return min(self._values) if self._values else 0

    def max(self) -> int:
        return max(self._values) if self._values else 0

    def all_values(self) -> List[int]:
        out: List[int] = []
        for value, count in sorted(self._values.items()):
            out.extend([value] * count)
        return out

    def __repr__(self) -> str:
        if self._count == 0:
            return "Histogram(empty)"
        return (
            f"Histogram(n={self._count}, mean={self.mean():.2f}, "
            f"p95={self.percentile(0.95):.0f}, p99={self.percentile(0.99):.0f})"
        )


class Metrics(Generic[K]):
    """Named histograms + aggregated counters (fantoch_prof/src/metrics/mod.rs:17-68)."""

    def __init__(self) -> None:
        self._collected: Dict[K, Histogram] = {}
        self._aggregated: Dict[K, int] = {}

    def collect(self, kind: K, value: int) -> None:
        self._collected.setdefault(kind, Histogram()).increment(value)

    def collect_many(self, kind: K, values) -> None:
        """Bulk histogram update from an array of values (one Counter merge
        instead of a Python call per command — the batched executor path)."""
        import numpy as np

        values = np.asarray(values)
        if values.size == 0:
            return
        uniq, counts = np.unique(values.astype(np.int64), return_counts=True)
        hist = self._collected.setdefault(kind, Histogram())
        for v, c in zip(uniq.tolist(), counts.tolist()):
            hist.increment(v, int(c))

    def aggregate(self, kind: K, by: int = 1) -> None:
        self._aggregated[kind] = self._aggregated.get(kind, 0) + by

    def get_collected(self, kind: K) -> Optional[Histogram]:
        return self._collected.get(kind)

    def get_aggregated(self, kind: K) -> Optional[int]:
        return self._aggregated.get(kind)

    def merge(self, other: "Metrics[K]") -> None:
        for kind, hist in other._collected.items():
            self._collected.setdefault(kind, Histogram()).merge(hist)
        for kind, count in other._aggregated.items():
            self._aggregated[kind] = self._aggregated.get(kind, 0) + count

    @property
    def collected(self) -> Dict[K, Histogram]:
        return self._collected

    @property
    def aggregated(self) -> Dict[K, int]:
        return self._aggregated

    def __repr__(self) -> str:
        return f"Metrics(aggregated={self._aggregated}, collected={self._collected})"
