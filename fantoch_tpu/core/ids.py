"""Identifier types.

TPU-native rebuild of the reference id layer (fantoch/src/id.rs:7-187):
``ProcessId``/``ClientId`` are plain ints, ``Dot`` (proposal identifier) and
``Rifl`` (request identifier for load balancing) are (source, sequence)
pairs.  Unlike the reference's generic ``Id<S>`` struct, we represent ids as
lightweight frozen dataclasses on the host control plane and as ``int32[2]``
(or packed ``int64``) lanes on device — see :mod:`fantoch_tpu.ops.frontier`.
"""

from __future__ import annotations

import itertools
from typing import Iterator, NamedTuple, Tuple

# Process ids are small ints (reference uses u8); shard ids are ints (u64).
ProcessId = int
ClientId = int
ShardId = int


class Dot(NamedTuple):
    """Proposal identifier: (source process, per-source sequence).

    Reference: fantoch/src/id.rs:12 (``Dot = Id<ProcessId>``).  Ordering is
    lexicographic (source, sequence), matching the reference's derived Ord —
    this ordering is what makes SCC-internal execution order deterministic.

    A NamedTuple, not a frozen dataclass: dots materialize per command on
    every executor/protocol hot path and tuple construction is ~3x
    cheaper than a frozen dataclass's two ``object.__setattr__`` calls;
    ordering, equality, and hashing are field-lexicographic either way.
    """

    source: ProcessId
    sequence: int

    def __str__(self) -> str:  # e.g. "2.17", mirrors Display "source.sequence"
        return f"{self.source}.{self.sequence}"

    def target_shard(self, n: int) -> ShardId:
        """Shard that owns this dot under the id layout of util.process_ids.

        Reference: fantoch/src/id.rs:59-63 — process ids are laid out so shard
        ``s`` owns ids ``s*n+1..=(s+1)*n``.
        """
        return (self.source - 1) // n

    def packed(self) -> int:
        """Pack into a single int (source in high bits) for device tensors."""
        return (self.source << 48) | self.sequence

    @staticmethod
    def unpack(packed: int) -> "Dot":
        return Dot(packed >> 48, packed & ((1 << 48) - 1))


class Rifl(NamedTuple):
    """Request identifier: (client id, client-local sequence).

    Reference: fantoch/src/id.rs:16 (``Rifl = Id<ClientId>``).
    NamedTuple for the same hot-path reason as :class:`Dot`.
    """

    source: ClientId
    sequence: int

    def __str__(self) -> str:
        return f"{self.source}.{self.sequence}"


class IdGen:
    """Sequential id generator (fantoch/src/id.rs:65-92)."""

    def __init__(self, source: int):
        self._source = source
        self._seq = 0

    @property
    def source(self) -> int:
        return self._source

    def next_id(self) -> Dot:
        self._seq += 1
        return Dot(self._source, self._seq)


class RiflGen:
    """Like IdGen but producing Rifls."""

    def __init__(self, source: int):
        self._source = source
        self._seq = 0

    @property
    def source(self) -> int:
        return self._source

    def next_id(self) -> Rifl:
        self._seq += 1
        return Rifl(self._source, self._seq)


class AtomicIdGen:
    """Thread-safe id generator (fantoch/src/id.rs:95-131).

    The reference uses a lock-free AtomicU64; we use itertools.count which is
    atomic under the GIL, with a lock-free fast path.
    """

    def __init__(self, source: int):
        self._source = source
        self._counter = itertools.count(1)

    @property
    def source(self) -> int:
        return self._source

    def next_id(self) -> Dot:
        return Dot(self._source, next(self._counter))

    def resume_after(self, sequence: int) -> None:
        """Restart support: never hand out sequences at or below
        ``sequence`` (the WAL's recovered dot lease).  Boot-time only —
        callers must not race this with next_id."""
        self._counter = itertools.count(sequence + 1)


def process_ids(shard_id: ShardId, n: int) -> Iterator[ProcessId]:
    """Process ids of one shard: shard s owns ids s*n+1..=(s+1)*n.

    Reference: fantoch/src/util.rs:115-123.
    """
    start = shard_id * n + 1
    return iter(range(start, start + n))


def all_process_ids(shard_count: int, n: int) -> Iterator[Tuple[ProcessId, ShardId]]:
    """All (process id, shard id) pairs (fantoch/src/util.rs:125-132)."""
    for shard_id in range(shard_count):
        for process_id in process_ids(shard_id, n):
            yield process_id, shard_id
