"""Event-set clocks: the lattice used for executed/committed tracking and GC.

Host-side equivalent of the reference's `threshold` crate types:
- ``AboveExSet``: a set of events (positive ints) stored as a contiguous
  frontier plus an exception set of events above it.
- ``AEClock``: map actor -> AboveExSet (used as ``Executed``/committed
  clocks, e.g. fantoch/src/protocol/mod.rs:40).
- ``VClock``: map actor -> max event, i.e. a plain vector clock with
  join (pointwise max) and meet (pointwise min) — the meet across processes
  yields the stable frontier for GC (fantoch/src/protocol/gc.rs:120-137).

The device-side mirror of AEClock is a dense ``int64[n]`` frontier vector
plus a bounded exception buffer — see fantoch_tpu/ops/frontier.py.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, Iterator, Optional, Set, Tuple, TypeVar

A = TypeVar("A", bound=Hashable)


class AboveExSet:
    """Frontier + above-frontier exceptions event set."""

    __slots__ = ("_frontier", "_above")

    def __init__(self, frontier: int = 0, above: Optional[Set[int]] = None):
        self._frontier = frontier
        self._above: Set[int] = above or set()

    def add(self, event: int) -> bool:
        """Add an event; returns True if newly added."""
        if event <= self._frontier or event in self._above:
            return False
        if event == self._frontier + 1:
            self._frontier = event
            # absorb contiguous exceptions
            while self._frontier + 1 in self._above:
                self._frontier += 1
                self._above.discard(self._frontier)
        else:
            self._above.add(event)
        return True

    def add_range(self, start: int, end: int) -> None:
        for event in range(start, end + 1):
            self.add(event)

    def contains(self, event: int) -> bool:
        return event <= self._frontier or event in self._above

    @property
    def frontier(self) -> int:
        """Highest event such that all events up to it are present."""
        return self._frontier

    def join(self, other: "AboveExSet") -> None:
        for event in other.events():
            self.add(event)

    def events(self) -> Iterator[int]:
        yield from range(1, self._frontier + 1)
        yield from sorted(self._above)

    def event_count(self) -> int:
        return self._frontier + len(self._above)

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, AboveExSet)
            and self._frontier == other._frontier
            and self._above == other._above
        )

    def __repr__(self) -> str:
        return f"AboveExSet({self._frontier}, +{sorted(self._above)})"

    def copy(self) -> "AboveExSet":
        return AboveExSet(self._frontier, set(self._above))


class RangeEventSet:
    """Event set stored as sorted disjoint ranges — O(log r) adds for
    arbitrarily wide ranges.

    Newt vote ranges span real-time microsecond clocks (ranges of millions
    of events per bump, fantoch_ps/src/protocol/newt.rs clock-bump), so the
    per-event ``AboveExSet`` representation is unusable there; this is the
    analog of the threshold crate's ``ARClock`` event sets.
    """

    __slots__ = ("_ranges",)

    def __init__(self) -> None:
        # sorted, disjoint, non-adjacent [start, end] (inclusive) ranges
        self._ranges: list = []

    def add_range(self, start: int, end: int) -> bool:
        """Union [start, end] in; returns True if any event was new."""
        assert start <= end
        import bisect

        ranges = self._ranges
        # first range that could touch [start, end]: rightmost with
        # range_start <= end + 1, scanning left while overlapping/adjacent
        lo = bisect.bisect_left(ranges, (start,))
        if lo > 0 and ranges[lo - 1][1] >= start - 1:
            lo -= 1
        hi = lo
        new_start, new_end = start, end
        while hi < len(ranges) and ranges[hi][0] <= end + 1:
            r_start, r_end = ranges[hi]
            new_start = min(new_start, r_start)
            new_end = max(new_end, r_end)
            hi += 1
        if hi == lo:
            ranges.insert(lo, (start, end))
            return True
        covered = hi - lo == 1 and ranges[lo][0] <= start and ranges[lo][1] >= end
        ranges[lo:hi] = [(new_start, new_end)]
        return not covered

    def contains(self, event: int) -> bool:
        import bisect

        i = bisect.bisect_right(self._ranges, (event, float("inf")))
        return i > 0 and self._ranges[i - 1][1] >= event

    @property
    def frontier(self) -> int:
        """Highest event e with 1..=e all present."""
        if self._ranges and self._ranges[0][0] == 1:
            return self._ranges[0][1]
        return 0

    def event_count(self) -> int:
        return sum(end - start + 1 for start, end in self._ranges)

    def ranges(self):
        return list(self._ranges)

    def __repr__(self) -> str:
        return f"RangeEventSet({self._ranges})"


class AEClock(Generic[A]):
    """Above-exception clock: actor -> AboveExSet."""

    def __init__(self, actors: Iterable[A] = ()):  # bottom clock over actors
        self._clock: Dict[A, AboveExSet] = {actor: AboveExSet() for actor in actors}

    def add(self, actor: A, event: int) -> bool:
        return self._clock.setdefault(actor, AboveExSet()).add(event)

    def add_range(self, actor: A, start: int, end: int) -> None:
        self._clock.setdefault(actor, AboveExSet()).add_range(start, end)

    def contains(self, actor: A, event: int) -> bool:
        eset = self._clock.get(actor)
        return eset is not None and eset.contains(event)

    def get(self, actor: A) -> Optional[AboveExSet]:
        return self._clock.get(actor)

    def frontier(self) -> "VClock[A]":
        """VClock of contiguous frontiers."""
        out: VClock[A] = VClock()
        for actor, eset in self._clock.items():
            out.set(actor, eset.frontier)
        return out

    def join(self, other: "AEClock[A]") -> None:
        for actor, eset in other._clock.items():
            self._clock.setdefault(actor, AboveExSet()).join(eset)

    def actors(self) -> Iterator[A]:
        return iter(self._clock.keys())

    def event_count(self) -> int:
        return sum(e.event_count() for e in self._clock.values())

    def __len__(self) -> int:
        return len(self._clock)

    def __eq__(self, other: object) -> bool:
        return isinstance(other, AEClock) and self._clock == other._clock

    def __repr__(self) -> str:
        return f"AEClock({self._clock})"

    def copy(self) -> "AEClock[A]":
        out: AEClock[A] = AEClock()
        out._clock = {a: e.copy() for a, e in self._clock.items()}
        return out


class VClock(Generic[A]):
    """Plain vector clock: actor -> max contiguous event."""

    def __init__(self, actors: Iterable[A] = ()):  # bottom clock over actors
        self._clock: Dict[A, int] = {actor: 0 for actor in actors}

    def set(self, actor: A, event: int) -> None:
        self._clock[actor] = event

    def add(self, actor: A, event: int) -> None:
        """Monotone add: only moves the entry forward."""
        if event > self._clock.get(actor, 0):
            self._clock[actor] = event

    def get(self, actor: A) -> int:
        return self._clock.get(actor, 0)

    def contains(self, actor: A, event: int) -> bool:
        return event <= self._clock.get(actor, 0)

    def join(self, other: "VClock[A]") -> None:
        """Pointwise max."""
        for actor, event in other._clock.items():
            if event > self._clock.get(actor, 0):
                self._clock[actor] = event

    def meet(self, other: "VClock[A]") -> None:
        """Pointwise min over this clock's actors (intersection frontier)."""
        for actor in self._clock:
            self._clock[actor] = min(self._clock[actor], other._clock.get(actor, 0))

    def actors(self) -> Iterator[A]:
        return iter(self._clock.keys())

    def items(self) -> Iterator[Tuple[A, int]]:
        return iter(self._clock.items())

    def __eq__(self, other: object) -> bool:
        return isinstance(other, VClock) and self._clock == other._clock

    def __repr__(self) -> str:
        return f"VClock({self._clock})"

    def copy(self) -> "VClock[A]":
        out: VClock[A] = VClock()
        out._clock = dict(self._clock)
        return out
