"""Time sources: wall clock for the real runner, virtual clock for the sim.

Reference: fantoch/src/time.rs:3-111 (``SysTime`` trait, ``RunTime``,
``SimTime``).
"""

from __future__ import annotations

import time as _time
from typing import Protocol


class SysTime(Protocol):
    def millis(self) -> int: ...

    def micros(self) -> int: ...


class RunTime:
    """Wall-clock time (fantoch/src/time.rs:9-27)."""

    def millis(self) -> int:
        return _time.time_ns() // 1_000_000

    def micros(self) -> int:
        return _time.time_ns() // 1_000


class SimTime:
    """Settable monotonic virtual clock (fantoch/src/time.rs:30-78).

    Stored in milliseconds; ``micros`` derives from it so simulated
    timestamps are consistent across both granularities.
    """

    def __init__(self, start_millis: int = 0):
        self._millis = start_millis

    def set_millis(self, millis: int) -> None:
        assert millis >= self._millis, "simulation time must be monotonically non-decreasing"
        self._millis = millis

    def add_millis(self, millis: int) -> None:
        assert millis >= 0, "simulation time must be monotonically non-decreasing"
        self._millis += millis

    def millis(self) -> int:
        return self._millis

    def micros(self) -> int:
        return self._millis * 1000
