"""System configuration and per-protocol quorum-size formulas.

Reference: fantoch/src/config.rs:7-317.  One flat config struct shared by all
protocols, drivers, and executors.  Quorum-size formulas are protocol facts
(from the EPaxos/Atlas/Tempo/Caesar papers) and must match the reference
exactly — the reference's own formula tests (fantoch/src/config.rs:320-538)
are mirrored in tests/test_config.py.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

from fantoch_tpu.core.ids import ProcessId


@dataclass
class Config:
    """Flat system config (fantoch/src/config.rs:7-43).

    Attributes mirror the reference's knobs; durations are in milliseconds.
    """

    # number of processes (per shard) and max tolerated faults
    n: int
    f: int
    # number of shards (partial replication); 1 = full replication
    shard_count: int = 1
    # if True, commands are executed at commit time by the protocol itself
    # (skipping the executor's ordering) — only safe for benchmarks
    execute_at_commit: bool = False
    # interval at which executors inform workers of executed commands
    # (drives dot-based GC); None disables the notification (default 5ms,
    # fantoch/src/config.rs:58-61)
    executor_executed_notification_interval_ms: Optional[int] = 5
    # interval at which executors clean up / retry cross-shard requests
    executor_cleanup_interval_ms: Optional[int] = 5
    # interval at which executors check for stuck commands (liveness watchdog)
    executor_monitor_pending_interval_ms: Optional[int] = None
    # bounded wait: a command pending on *missing* (never-committed)
    # dependencies past this threshold raises a typed StalledExecutionError
    # from the watchdog instead of hanging — the crash-tolerance contract
    # for deps owned by dead replicas (None keeps the log-only behavior)
    executor_pending_fail_ms: Optional[int] = None
    # bounded wait before a process starts per-dot recovery consensus for a
    # committed-overdue dot (MPrepare/MPromise over the embedded synod):
    # the dot's owner retries first, ring successors stagger in afterwards.
    # Pick it SMALLER than executor_pending_fail_ms so recovery races ahead
    # of the executor watchdog (None disables recovery — the reference's
    # todo!() behavior)
    recovery_delay_ms: Optional[int] = None
    # FPaxos leader failover: followers suspect a silent leader after this
    # bound (ring successors stagger by distance) and run MultiSynod
    # prepare/promise with accepted-slot carry-forward; the leader
    # heartbeats at a quarter of it (None disables failover)
    fpaxos_leader_timeout_ms: Optional[int] = None
    # record per-key execution order for agreement checks in tests
    executor_monitor_execution_order: bool = False
    # order committed commands with the batched device resolver
    # (fantoch_tpu/executor/graph/batched.py) instead of the host Tarjan
    # walk — the TPU-native replacement for tarjan.rs:99-319 (new knob; no
    # reference counterpart)
    batched_graph_executor: bool = False
    # batch the Newt/Tempo table path: array-backed key clocks with
    # kernel-batched proposals (protocol/common/table_batched.py) and one
    # vectorized stability pass per executor batch
    # (fantoch_tpu/ops/table_ops.py at the executor/table.py seam)
    batched_table_executor: bool = False
    # device-resident votes-table plane: the TableExecutor keeps the
    # (key_bucket x process) vote-frontier matrix on device across
    # batches (donated buffers) and runs vote coalescing + frontier
    # update + stability as ONE fused dispatch per batch
    # (executor/table_plane.py over ops/table_ops.fused_votes_commit).
    # Requires clocks below 2^31 (no real-time-micros clock bumps)
    device_table_plane: bool = False
    # frontier-matrix element count (keys x n) at which the TableExecutor
    # host path routes stability to the device kernel instead of the
    # numpy partition.  None = the built-in default (1 << 20), overridable
    # via the FANTOCH_TABLE_KERNEL_THRESHOLD env var; an explicit value
    # here beats both
    table_kernel_threshold: Optional[int] = None
    # batch Caesar's predecessor executor: two-phase countdown resolution
    # as one device kernel per batch (fantoch_tpu/ops/pred_resolve.py at
    # the executor/pred.py seam)
    batched_pred_executor: bool = False
    # device-resident predecessors plane for Caesar: the
    # PredecessorsExecutor keeps the whole pending window (sparse
    # predecessor sets as an int32[C, W] slot matrix + clock columns) on
    # device across batches with donated in-place state, one fused
    # dispatch per feed; missing-blocked rows stay resident and wake
    # when their deps commit (executor/pred_plane.py over
    # ops/pred_resolve.resolve_pred_plane_step).  Caesar additionally
    # routes commits through a column builder (one PredExecutionArrays
    # drain per to_executors sweep).  Requires timestamp sequences below
    # 2^31 (guarded with a typed ClockOverflowError)
    device_pred_plane: bool = False
    # device-resident graph plane for EPaxos/Atlas: the batched graph
    # executor keeps its dependency backlog (src/seq/key columns plus the
    # dep-slot matrix) ON DEVICE across feeds (executor/graph/
    # graph_plane.py over ops/graph_resolve.resolve_graph_plane_step):
    # feeds install new rows and patch MISSING cells in place, resolves
    # run as donated in-place dispatches with only the emitted order
    # fetched back, and missing-blocked rows stay resident instead of
    # round-tripping through host columns.  None = the
    # FANTOCH_GRAPH_PLANE env var, else off (the host-column path stays
    # the default oracle twin).  Single-shard only (shard sets must
    # survive on host for cross-shard requests); requires
    # batched_graph_executor
    device_graph_plane: Optional[bool] = None
    # backlog size at which the batched graph executor stops collecting
    # exact per-SCC structure metrics (CHAIN_SIZE) and switches the
    # multi-key path to the resident peeler / the host path to the
    # arrival-order shortcut.  None = the FANTOCH_GRAPH_KERNEL_THRESHOLD
    # env var, else the built-in 4096; an explicit value here beats both
    # (the Config.table_kernel_threshold precedence, resolved through
    # executor/device_plane.resolve_threshold)
    graph_kernel_threshold: Optional[int] = None
    # resolver choice for the batched graph executor on *CPU* backends:
    # None = auto (the native C++ SCC resolver, fantoch_tpu/native, when
    # its toolchain is available — a single-threaded host loop beats CPU
    # XLA sorts; accelerator backends always use the device kernels),
    # True/False force it on/off (tests pin the XLA path with False)
    host_native_resolver: Optional[bool] = None
    # accelerator fault tolerance (executor/device_plane.py): per-dispatch
    # deadline in wall ms — a fused dispatch (including its blocking
    # drain) overrunning it raises a typed DeviceFailedError inside the
    # plane, which fails over to the host twin and rebuilds.  Setting it
    # ARMS the fault plane: the plane starts keeping the host-twin
    # dispatch log failover replays from.  None (default) = unarmed, the
    # plane trusts the device unconditionally (zero overhead)
    device_dispatch_timeout_ms: Optional[float] = None
    # Pallas-fused resolve kernels (ops/pallas_resolve.py): route the
    # hot plane dispatches (graph/pred plane step, fused table round,
    # votes commit) through hand-fused Pallas kernels instead of the
    # XLA-composed programs.  None = the FANTOCH_PALLAS env var, else
    # the backend default (on for TPU, off elsewhere — on CPU the
    # kernels run in interpret mode, a parity instrument not a perf
    # win).  Bit-for-bit either way; unsupported backends fall back to
    # the composed programs automatically.  Process-global (the routers
    # are module-level): co-hosted executors share one route
    pallas_kernels: Optional[bool] = None
    # persistent XLA compilation-cache directory
    # (core/compile_cache.py): an explicit path here beats the
    # FANTOCH_COMPILE_CACHE_DIR env var, which beats the obs-dir /
    # repo-adjacent defaults.  None = resolve through env/defaults
    compile_cache_dir: Optional[str] = None
    # sampled shadow-check rate in [0, 1]: with probability p per
    # dispatch (seeded, deterministic) the plane replays the dispatch's
    # inputs through the same kernel on host-owned twin state and
    # compares the resident post-state bit-for-bit — silent corruption
    # of a resident buffer surfaces as a typed DeviceCorruptionError
    # naming the first diverging row, instead of as a cross-replica
    # digest mismatch minutes later.  1.0 catches corruption on the very
    # dispatch it happens (the fuzz/test setting); production rates
    # trade detection latency for dispatch cost, with the PR 9
    # execution-digest auditor as the backstop.  > 0 arms the fault
    # plane like the deadline does
    plane_shadow_rate: float = 0.0
    # garbage-collection interval; None disables GC
    gc_interval_ms: Optional[int] = None
    # leader process (leader-based protocols, i.e. FPaxos)
    leader: Optional[ProcessId] = None
    # Newt (Tempo) knobs
    newt_tiny_quorums: bool = False
    newt_clock_bump_interval_ms: Optional[int] = None
    newt_detached_send_interval_ms: Optional[int] = None
    # Caesar knob: wait-condition on (True = the full protocol)
    caesar_wait_condition: bool = True
    # skip sending MCollectAck to the coordinator when the process is in the
    # fast quorum and the coordinator will ack anyway
    skip_fast_ack: bool = False
    # device serving pipeline depth (run/pipeline.py): how many
    # dispatched-but-undrained device rounds the serving loop keeps in
    # flight, overlapping host<->device transfer and result emit with
    # device compute (depth K = K rounds of delivery lag).  None = the
    # FANTOCH_SERVING_PIPELINE_DEPTH env var, else 1 (the classic
    # double-buffered overlap); an explicit value also opts the
    # DeviceRuntime into pipelining on CPU backends (new knob; no
    # reference counterpart — the reference's runner is message-at-a-time)
    serving_pipeline_depth: Optional[int] = None
    # adaptive ingest batching at the serving edge (run/ingest.py): the
    # deadline budget (ms) a queued submission may wait for its round to
    # fill before it is released anyway.  One knob like
    # serving_pipeline_depth: None = the FANTOCH_INGEST_DEADLINE_MS env
    # var, else 2.0; an explicit 0 disables batching (legacy
    # dispatch-on-anything).  The size target adapts from the EWMA
    # arrival rate unless ingest_target pins it; a lone command in an
    # otherwise idle system always dispatches immediately (the sync-
    # latency fast path), whatever these knobs say
    ingest_deadline_ms: Optional[float] = None
    # fixed ingest size target (rows that release a round) overriding
    # the EWMA-adaptive target.  None = the FANTOCH_INGEST_TARGET env
    # var, else adaptive
    ingest_target: Optional[int] = None
    # ceiling on the auto-tuned serving chain length S (rounds fused per
    # device dispatch, NewtDeviceDriver.step_chained_pipelined): the
    # tuner grows S while per-round dispatch overhead dominates device
    # time and never past this.  None = the FANTOCH_SERVING_CHAIN_MAX
    # env var, else 8; 1 disables chaining
    serving_chain_max: Optional[int] = None
    # durable command-log fsync policy (run/wal.py): "always" fsyncs
    # every append (commit-durable before anything acks it), "interval"
    # fsyncs on the runtime's periodic WAL tick (bounded loss window),
    # "never" leaves durability to the OS.  One knob like
    # serving_pipeline_depth: None = the FANTOCH_WAL_SYNC env var, else
    # "interval"; an explicit value here beats both.  Only consulted when
    # a runtime is given a wal_dir (new knob; no reference counterpart —
    # the reference's runner has no durability story)
    wal_sync: Optional[str] = None
    # overload-control plane (run/backpressure.py).  queue_capacity is
    # the high watermark of every run-layer bounded queue (worker /
    # executor pools, peer-writer queues, client reply queues): past it
    # the queue closes its credit gate and upstream socket readers pause
    # (pressure propagates peer-to-peer via TCP instead of as unbounded
    # heap); the gate re-opens once drained below half.  None = the
    # built-in default (backpressure.DEFAULT_QUEUE_CAPACITY, 8192);
    # 0 = unbounded legacy warn-only queues.  The reference's channels
    # warn-then-BLOCK on full (fantoch/src/run/task/chan.rs:36-58);
    # producers here share one cooperative loop, so the plane is
    # credit-based pause/resume plus shedding, never blocking puts
    queue_capacity: Optional[int] = None
    # admission control at the client-facing edge: when the serving
    # queue depth reaches this bound, new submissions are rejected with
    # a typed Overloaded reply (errors.OverloadedError client-side)
    # carrying a retry-after hint, instead of queueing without bound.
    # None disables shedding (the legacy accept-everything behavior)
    admission_limit: Optional[int] = None
    # base retry-after hint stamped on Overloaded replies; the server
    # scales it by how far past the admission limit the queue sits
    overload_retry_after_ms: int = 100
    # cap on a live-but-slow peer link's unacked resend window
    # (run/links.py): past it the link is declared lost through the
    # existing typed PeerLostError -> quorum-check path instead of
    # buffering unboundedly.  None = the built-in default
    # (backpressure.DEFAULT_UNACKED_CAP); 0 = uncapped legacy
    link_unacked_cap: Optional[int] = None
    # consistency-audit plane (core/audit.py).  execution_digests keeps a
    # per-key hash chain over executed writes inside every executor's
    # KVStore; the run layer piggybacks chain summaries on the heartbeat
    # path and surfaces a typed DivergenceError naming the first
    # diverging key + entry when replicas fork (run/process_runner.py).
    # Audit/chaos instrumentation, off by default (new knob; the
    # reference has no online safety checking)
    execution_digests: bool = False
    # record every commit decision (dot/slot -> (rifl, value)) in a log
    # that survives GC, so the ConsistencyAuditor can check commit-value
    # agreement (Newt timestamps, graph deps, FPaxos slots) and classify
    # committed-then-lost commands.  Audit/test only: the log grows with
    # the run (like executor_monitor_execution_order)
    audit_log_commits: bool = False
    # live telemetry plane (observability/timeseries.py): the ONE window
    # cadence every telemetry writer in a process runs at — the windowed
    # series emit, the legacy metrics snapshot, and the sim runner's
    # virtual-time telemetry tick all share it.  None = the runtime's
    # metrics_interval_ms argument (run layer) or the built-in 1s window
    # (sim).  Milliseconds, >= 1 (new knob; no reference counterpart —
    # fantoch_prof only ships post-hoc aggregates)
    telemetry_interval_ms: Optional[int] = None
    # per-dot lifecycle tracing (fantoch_tpu/observability): fraction of
    # commands traced, selected by a deterministic hash of the command id
    # (same seed => same sampled dot set).  0.0 disables tracing entirely
    # (runners install the zero-cost no-op tracer); runners also need a
    # trace destination (sim `trace_path` / run `trace_file`) to emit
    trace_sample_rate: float = 0.0
    # failure flight recorder (observability/recorder.py): keep a bounded
    # in-memory ring of recent UNSAMPLED trace events per process, dumped
    # as flight_p<pid>.json on typed failures (DivergenceError,
    # StalledExecutionError, quorum loss), WAL-restart boots, and
    # SIGUSR1 — the black box every failure ships with.  Ring capacity
    # is FANTOCH_FLIGHT_EVENTS (default 65536 events).  Off by default:
    # recording costs one dict append per hook-site event (new knob; no
    # reference counterpart)
    flight_recorder: bool = False

    def __post_init__(self) -> None:
        # reference panics if f > n/2 only in specific protocols; the config
        # itself only validates basic sanity (fantoch/src/config.rs:45-60)
        if self.n == 0:
            raise ValueError("n must be positive")
        if self.f > self.n:
            raise ValueError(f"f = {self.f} must not exceed n = {self.n}")
        if (
            self.serving_pipeline_depth is not None
            and self.serving_pipeline_depth < 1
        ):
            raise ValueError(
                f"serving_pipeline_depth = {self.serving_pipeline_depth} "
                "must be >= 1"
            )
        if self.ingest_deadline_ms is not None and self.ingest_deadline_ms < 0:
            raise ValueError(
                f"ingest_deadline_ms = {self.ingest_deadline_ms} must be "
                ">= 0 (0 = batching off)"
            )
        if self.ingest_target is not None and self.ingest_target < 1:
            raise ValueError(
                f"ingest_target = {self.ingest_target} must be >= 1"
            )
        if self.serving_chain_max is not None and self.serving_chain_max < 1:
            raise ValueError(
                f"serving_chain_max = {self.serving_chain_max} must be >= 1 "
                "(1 = chaining off)"
            )
        if self.wal_sync is not None and self.wal_sync not in (
            "always", "interval", "never",
        ):
            raise ValueError(
                f"wal_sync = {self.wal_sync!r} must be one of "
                "'always' | 'interval' | 'never'"
            )
        if self.queue_capacity is not None and self.queue_capacity < 0:
            raise ValueError(
                f"queue_capacity = {self.queue_capacity} must be >= 0 "
                "(0 = unbounded)"
            )
        if self.queue_capacity is not None and self.queue_capacity == 1:
            raise ValueError("queue_capacity = 1 cannot hold a burst; use >= 2")
        if self.admission_limit is not None and self.admission_limit < 1:
            raise ValueError(
                f"admission_limit = {self.admission_limit} must be >= 1"
            )
        if self.overload_retry_after_ms < 1:
            raise ValueError(
                f"overload_retry_after_ms = {self.overload_retry_after_ms} "
                "must be >= 1"
            )
        if self.link_unacked_cap is not None and self.link_unacked_cap < 0:
            raise ValueError(
                f"link_unacked_cap = {self.link_unacked_cap} must be >= 0 "
                "(0 = uncapped)"
            )
        if self.telemetry_interval_ms is not None and self.telemetry_interval_ms < 1:
            raise ValueError(
                f"telemetry_interval_ms = {self.telemetry_interval_ms} "
                "must be >= 1"
            )
        if self.device_graph_plane and not self.batched_graph_executor:
            # the plane lives inside BatchedDependencyGraph: without the
            # batched executor the knob would silently do nothing
            raise ValueError(
                "device_graph_plane requires batched_graph_executor"
            )
        if self.graph_kernel_threshold is not None and self.graph_kernel_threshold < 1:
            raise ValueError(
                f"graph_kernel_threshold = {self.graph_kernel_threshold} "
                "must be >= 1"
            )
        if (
            self.device_dispatch_timeout_ms is not None
            and self.device_dispatch_timeout_ms <= 0
        ):
            raise ValueError(
                f"device_dispatch_timeout_ms = "
                f"{self.device_dispatch_timeout_ms} must be > 0 "
                "(None = deadline off)"
            )
        if not (0.0 <= self.plane_shadow_rate <= 1.0):
            raise ValueError(
                f"plane_shadow_rate = {self.plane_shadow_rate} must be "
                "in [0, 1]"
            )
        if self.device_table_plane and self.newt_clock_bump_interval_ms is not None:
            # real-time clock bumps vote wall-clock micros, which overflow
            # the plane's 31-bit device-clock window (ops/table_ops.py)
            raise ValueError(
                "device_table_plane is incompatible with "
                "newt_clock_bump_interval_ms (real-time micros clocks "
                "exceed the 31-bit device window)"
            )

    # --- quorum sizes (protocol facts; fantoch/src/config.rs:252-317) ---

    def basic_quorum_size(self) -> int:
        return self.f + 1

    def fpaxos_quorum_size(self) -> int:
        return self.f + 1

    def atlas_quorum_sizes(self) -> Tuple[int, int]:
        """(fast_quorum_size, write_quorum_size) = (n//2 + f, f + 1)."""
        return (self.n // 2 + self.f, self.f + 1)

    def epaxos_quorum_sizes(self) -> Tuple[int, int]:
        """EPaxos always tolerates a minority: f = n//2.

        fast quorum = f + floor((f+1)/2)  (i.e. f + ceil(f/2) for the paper's
        3n/4-ish quorum), write quorum = f + 1.
        """
        f = self.n // 2
        return (f + (f + 1) // 2, f + 1)

    def caesar_quorum_sizes(self) -> Tuple[int, int]:
        """(fast, write) = (3n//4 + 1, n//2 + 1)."""
        return (3 * self.n // 4 + 1, self.n // 2 + 1)

    def newt_quorum_sizes(self) -> Tuple[int, int, int]:
        """(fast_quorum_size, write_quorum_size, stability_threshold).

        Stability threshold is ``n - fast_quorum_size + f``: it plus the
        minimum number of processes where clocks are computed
        (fast_quorum_size - f + 1) must exceed n.  With tiny quorums the fast
        quorum is 2f (clocks from f+1 processes), giving threshold n - f.
        """
        minority = self.n // 2
        if self.newt_tiny_quorums:
            fast, threshold = 2 * self.f, self.n - self.f
        else:
            fast, threshold = minority + self.f, minority + 1
        return (fast, self.f + 1, threshold)

    def with_(self, **kwargs) -> "Config":
        """Functional update helper."""
        return replace(self, **kwargs)
