"""Key-value store: the replicated state machine being ordered.

Reference: fantoch/src/kvs.rs:6-138.  ``Key``/``Value`` are strings; ops are
Get/Put/Delete with ``Optional[str]`` results.  The KVStore itself stays on
the host (it is control-plane: string keys, tiny values); the accelerator
works on *pre-hashed* int keys (see fantoch_tpu/ops) so the store never has
to cross the device boundary.
"""

from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import Dict, Optional, TYPE_CHECKING

if TYPE_CHECKING:
    from fantoch_tpu.core.audit import ExecutionDigest
    from fantoch_tpu.executor.monitor import ExecutionOrderMonitor
    from fantoch_tpu.core.ids import Rifl

Key = str
Value = str
KVOpResult = Optional[Value]


class KVOpKind(Enum):
    GET = "Get"
    PUT = "Put"
    DELETE = "Delete"


@dataclass(frozen=True)
class KVOp:
    """A single-key operation (fantoch/src/kvs.rs:12-16)."""

    kind: KVOpKind
    value: Optional[Value] = None  # only for PUT

    @staticmethod
    def get() -> "KVOp":
        return KVOp(KVOpKind.GET)

    @staticmethod
    def put(value: Value) -> "KVOp":
        return KVOp(KVOpKind.PUT, value)

    @staticmethod
    def delete() -> "KVOp":
        return KVOp(KVOpKind.DELETE)

    @property
    def is_read(self) -> bool:
        return self.kind is KVOpKind.GET


class KVStore:
    """In-memory string KV store (fantoch/src/kvs.rs:21-69)."""

    def __init__(
        self,
        monitor_execution_order: bool = False,
        execution_digests: bool = False,
    ):
        self._store: Dict[Key, Value] = {}
        self._monitor: Optional["ExecutionOrderMonitor"] = None
        if monitor_execution_order:
            from fantoch_tpu.executor.monitor import ExecutionOrderMonitor

            self._monitor = ExecutionOrderMonitor()
        # consistency-audit plane (core/audit.py): per-key hash chain
        # over executed writes, exchanged by the run layer for online
        # divergence detection (Config.execution_digests)
        self._digest: Optional["ExecutionDigest"] = None
        if execution_digests:
            from fantoch_tpu.core.audit import ExecutionDigest

            self._digest = ExecutionDigest()

    @property
    def monitor(self) -> Optional["ExecutionOrderMonitor"]:
        return self._monitor

    @property
    def digest(self) -> Optional["ExecutionDigest"]:
        return self._digest

    def execute(self, key: Key, op: KVOp, rifl: "Rifl") -> KVOpResult:
        """Execute op on key, recording it in the monitor if enabled.

        Reference: fantoch/src/kvs.rs:37-56 (monitored execute).
        """
        if self._monitor is not None:
            self._monitor.add(key, rifl, read=op.is_read)
        if self._digest is not None and not op.is_read:
            # writes only: reads commute, so their relative order is
            # legitimately unordered across replicas (the monitor's
            # write-order rule)
            self._digest.record(key, rifl, op.kind.value, op.value)
        return self._do_execute(key, op)

    def _do_execute(self, key: Key, op: KVOp) -> KVOpResult:
        if op.kind is KVOpKind.GET:
            return self._store.get(key)
        if op.kind is KVOpKind.PUT:
            # Returns the previous value, like the reference's HashMap::insert.
            assert op.value is not None
            return self._put(key, op.value)
        if op.kind is KVOpKind.DELETE:
            return self._store.pop(key, None)
        raise AssertionError(f"unknown op kind {op.kind}")

    def _put(self, key: Key, value: Value) -> KVOpResult:
        prev = self._store.get(key)
        self._store[key] = value
        return prev
