"""Multi-shard, multi-key commands and their (partially aggregated) results.

Reference: fantoch/src/command.rs:12-262.  A command is a map
``shard -> key -> op`` identified by a Rifl; conflict = key intersection;
results aggregate per-key op results until all keys have reported.
"""

from __future__ import annotations

from typing import Dict, Iterator, Tuple, TYPE_CHECKING

from fantoch_tpu.core.ids import Rifl, ShardId
from fantoch_tpu.core.kvs import KVOp, KVOpResult, Key, KVStore

if TYPE_CHECKING:
    from fantoch_tpu.executor.base import ExecutorResult


class Command:
    """A client command spanning one or more shards (fantoch/src/command.rs:12-170)."""

    __slots__ = ("_rifl", "_shard_to_ops", "_read_only", "_total_key_count")

    def __init__(self, rifl: Rifl, shard_to_ops: Dict[ShardId, Dict[Key, Tuple[KVOp, ...]]]):
        assert shard_to_ops, "commands must have at least one shard"
        self._rifl = rifl
        self._shard_to_ops = shard_to_ops
        # read_only inference (fantoch/src/command.rs:28-36): a command is
        # read-only iff every op on every key is a read.  One pass over the
        # ops — this constructor sits on the client submit path, so no
        # intermediate list / multiple scans.
        reads = 0
        writes = 0
        total = 0
        for ops in shard_to_ops.values():
            total += len(ops)
            for key_ops in ops.values():
                for op in key_ops:
                    if op.is_read:
                        reads += 1
                    else:
                        writes += 1
        self._read_only = writes == 0
        # reference invariant (fantoch/src/command.rs:32-41): either all ops
        # are reads or none are — mixed commands break read-only fast paths
        assert reads == 0 or writes == 0, (
            "non-read-only commands cannot contain Get operations"
        )
        self._total_key_count = total

    @staticmethod
    def from_single(rifl: Rifl, shard_id: ShardId, key: Key, op: KVOp) -> "Command":
        # the dominant wire shape (one shard, one key, one op): the general
        # scan above degenerates to constants, so skip it — single-op
        # commands cannot violate the mixed-ops invariant
        cmd = Command.__new__(Command)
        cmd._rifl = rifl
        cmd._shard_to_ops = {shard_id: {key: (op,)}}
        cmd._read_only = op.is_read
        cmd._total_key_count = 1
        return cmd

    @staticmethod
    def from_keys(rifl: Rifl, shard_id: ShardId, key_ops: Dict[Key, Tuple[KVOp, ...]]) -> "Command":
        return Command(rifl, {shard_id: dict(key_ops)})

    @property
    def rifl(self) -> Rifl:
        return self._rifl

    @property
    def read_only(self) -> bool:
        return self._read_only

    @property
    def shard_count(self) -> int:
        return len(self._shard_to_ops)

    def shards(self) -> Iterator[ShardId]:
        return iter(self._shard_to_ops.keys())

    def replicated_by(self, shard_id: ShardId) -> bool:
        return shard_id in self._shard_to_ops

    def multi_shard(self) -> bool:
        return len(self._shard_to_ops) > 1

    def key_count(self, shard_id: ShardId) -> int:
        """Number of keys accessed on `shard_id` (fantoch/src/command.rs:88)."""
        return len(self._shard_to_ops.get(shard_id, {}))

    @property
    def total_key_count(self) -> int:
        return self._total_key_count

    def keys(self, shard_id: ShardId) -> Iterator[Key]:
        """Keys accessed on a given shard (fantoch/src/command.rs:97-103)."""
        return iter(self._shard_to_ops.get(shard_id, {}).keys())

    def iter_ops(self, shard_id: ShardId) -> Iterator[Tuple[Key, Tuple[KVOp, ...]]]:
        """(key, ops) pairs for one shard (fantoch/src/command.rs into_iter)."""
        return iter(self._shard_to_ops.get(shard_id, {}).items())

    def all_keys(self) -> Iterator[Tuple[ShardId, Key]]:
        for shard_id, ops in self._shard_to_ops.items():
            for key in ops:
                yield shard_id, key

    def conflicts(self, other: "Command") -> bool:
        """Key-intersection conflict check (fantoch/src/command.rs:141-147)."""
        for shard_id, ops in self._shard_to_ops.items():
            other_ops = other._shard_to_ops.get(shard_id)
            if other_ops and not ops.keys().isdisjoint(other_ops.keys()):
                return True
        return False

    def execute(self, shard_id: ShardId, store: KVStore) -> List["ExecutorResult"]:
        """Execute this command's ops for `shard_id`, returning per-key results.

        Reference: fantoch/src/command.rs:114-127.  Returns a list (not a
        generator): this is the serving hot path — one call per executed
        command — and the dominant shape is a single key with a single op,
        which skips the genexpr entirely.
        """
        from fantoch_tpu.executor.base import ExecutorResult

        rifl = self._rifl
        out = []
        for key, key_ops in self._shard_to_ops.get(shard_id, {}).items():
            if len(key_ops) == 1:
                results = (store.execute(key, key_ops[0], rifl),)
            else:
                results = tuple(store.execute(key, op, rifl) for op in key_ops)
            out.append(ExecutorResult(rifl, key, results))
        return out

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Command)
            and self._rifl == other._rifl
            and self._shard_to_ops == other._shard_to_ops
        )

    def __hash__(self) -> int:
        return hash(self._rifl)

    def __repr__(self) -> str:
        keys = {s: sorted(ops) for s, ops in self._shard_to_ops.items()}
        return f"Command({self._rifl}, {keys})"


class CommandResult:
    """Partial aggregation of per-key results for one shard's portion.

    Reference: fantoch/src/command.rs:173-216.  Ready when `key_count` keys
    have reported.
    """

    __slots__ = ("_rifl", "_key_count", "_results")

    def __init__(self, rifl: Rifl, key_count: int):
        self._rifl = rifl
        self._key_count = key_count
        self._results: Dict[Key, Tuple[KVOpResult, ...]] = {}

    @property
    def rifl(self) -> Rifl:
        return self._rifl

    def add_partial(self, key: Key, result: Tuple[KVOpResult, ...]) -> bool:
        """Add one key's results; returns True once the result is ready."""
        assert key not in self._results, f"duplicate partial result for {key}"
        self._results[key] = result
        return self.ready

    def increment_key_count(self, by: int = 1) -> None:
        """Raise the number of expected partials (fantoch/src/command.rs:203)."""
        self._key_count += by

    @property
    def ready(self) -> bool:
        return len(self._results) == self._key_count

    @property
    def results(self) -> Dict[Key, Tuple[KVOpResult, ...]]:
        return self._results

    def merge(self, other: "CommandResult") -> None:
        """Merge results from another shard (used by ShardsPending aggregation)."""
        assert self._rifl == other._rifl
        self._key_count += other._key_count
        for key, res in other._results.items():
            assert key not in self._results
            self._results[key] = res

    def __repr__(self) -> str:
        return f"CommandResult({self._rifl}, {len(self._results)}/{self._key_count})"
