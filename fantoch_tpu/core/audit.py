"""Consistency auditing: one reusable invariant engine for cross-replica
safety, plus the per-key chained execution digests the run layer exchanges
for online divergence detection.

Every chaos/restart test before this module asserted *completion*; the
actual safety claim of the protocols — replicas execute conflicting
commands in the same order, exactly once, and never lose a committed
command — lived as scattered per-test assertions (tests/harness.py
``check_monitors`` plus ad-hoc checks).  The reference leans on stateright
+ quickcheck for this class of bug; our exhaustive checker (mc/checker.py)
is capped at n=3/f=1 and cannot reach WAL/overload/SlowProcess
interleavings.  This module is the scalable instrument:

* :class:`ConsistencyAuditor` — protocol-agnostic post-run verdict over
  the executors' :class:`~fantoch_tpu.executor.monitor.ExecutionOrderMonitor`
  histories and (optionally) the protocols' audit commit logs
  (``Config.audit_log_commits``): per-key total-order agreement of
  conflicting writes, exactly-once execution per rifl, committed-then-lost
  detection, and per-dot commit-value agreement (Newt timestamp / graph
  deps / Caesar (clock, deps) / FPaxos slot->command).  Returns typed
  :class:`Violation` records carrying a *minimal counterexample* (the
  first diverging position, not whole histories).  The chaos fuzzer
  (sim/fuzz.py) runs it after every case; tests/harness.py delegates its
  agreement checks here so every existing sim test rides the same engine.

* :class:`ExecutionDigest` — a per-key hash chain over executed *writes*
  (reads commute and are excluded, mirroring the monitor's write-order
  rule), maintained inside every executor's KVStore when
  ``Config.execution_digests`` is on.  Summaries (count, digest-at-count)
  are cheap to ship; a replica that is at least as far along on a key can
  verify the peer's whole prefix from its own chain.  The run layer
  piggybacks summaries on the heartbeat path and resolves a mismatch to
  the *first* diverging entry with one follow-up exchange
  (run/process_runner.py -> :class:`~fantoch_tpu.errors.DivergenceError`).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, NamedTuple, Optional, Tuple

from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.kvs import Key

# --- violation kinds ---

ORDER_DIVERGENCE = "order-divergence"
DUPLICATE_EXECUTION = "duplicate-execution"
MULTISET_DIVERGENCE = "multiset-divergence"
KEYSET_DIVERGENCE = "keyset-divergence"
COMMITTED_LOST = "committed-then-lost"
COMMIT_DIVERGENCE = "commit-divergence"


@dataclass(frozen=True)
class Violation:
    """One typed safety violation with its minimal counterexample.

    ``entries`` carries only the evidence needed to understand the
    failure (e.g. the first diverging position and the two rifls there),
    never whole histories — the shrinker (sim/fuzz.py) minimizes the
    *schedule*, this minimizes the *witness*."""

    kind: str
    detail: str
    key: Optional[Key] = None
    pids: Tuple[int, ...] = ()
    entries: Tuple[Any, ...] = ()

    def __str__(self) -> str:
        where = f" key={self.key!r}" if self.key is not None else ""
        who = f" pids={list(self.pids)}" if self.pids else ""
        return f"[{self.kind}]{where}{who} {self.detail}"


@dataclass
class AuditVerdict:
    """The auditor's answer: ``ok`` iff no violation survived."""

    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def counterexample(self) -> Optional[Violation]:
        """The first (most load-bearing) violation, if any."""
        return self.violations[0] if self.violations else None

    def describe(self) -> str:
        if self.ok:
            return "audit clean"
        lines = [f"{len(self.violations)} consistency violation(s):"]
        lines.extend(f"  {v}" for v in self.violations)
        return "\n".join(lines)


class ConsistencyAuditor:
    """Protocol-agnostic safety checks over per-process execution
    histories (and optional commit logs).

    ``expected_ops_per_key`` bounds how many times one rifl may legally
    touch one key (our workloads issue one op per (command, key); pass
    None to disable the absolute duplicate check and rely on cross-replica
    asymmetry alone)."""

    def __init__(self, expected_ops_per_key: Optional[int] = 1):
        self.expected_ops_per_key = expected_ops_per_key

    # --- the one entry point ---

    def audit(
        self,
        monitors: Dict[int, Any],
        commit_logs: Optional[Dict[int, Dict[Any, Tuple[Optional[Rifl], Any]]]] = None,
    ) -> AuditVerdict:
        """Audit the execution-order monitors of a set of (surviving)
        replicas, plus their commit logs when available.  ``monitors``
        maps pid -> ExecutionOrderMonitor (all non-None)."""
        verdict = AuditVerdict()
        items = sorted(monitors.items())
        assert items, "audit requires at least one monitor"
        self._check_duplicates(items, verdict)
        self._check_keysets(items, verdict)
        self._check_write_orders(items, verdict)
        self._check_multisets(items, verdict, commit_logs)
        if commit_logs:
            self._check_commit_logs(commit_logs, verdict)
        return verdict

    # --- per-process checks ---

    def _check_duplicates(self, items, verdict: AuditVerdict) -> None:
        """Exactly-once execution: one rifl must not touch one key more
        often than the workload's op multiplicity allows (the PR 7
        GC-straggler commit REPLAY executed commands twice)."""
        if self.expected_ops_per_key is None:
            return
        from collections import Counter

        for pid, monitor in items:
            for key in monitor.keys():
                counts = Counter(monitor.get_order(key))
                for rifl, count in counts.items():
                    if count > self.expected_ops_per_key:
                        verdict.violations.append(
                            Violation(
                                DUPLICATE_EXECUTION,
                                f"{rifl} executed {count}x on p{pid} "
                                f"(expected <= {self.expected_ops_per_key})",
                                key=key,
                                pids=(pid,),
                                entries=(rifl, count),
                            )
                        )

    # --- cross-process checks ---

    def _check_keysets(self, items, verdict: AuditVerdict) -> None:
        all_keys = set()
        for _pid, monitor in items:
            all_keys.update(monitor.keys())
        for key in sorted(all_keys):
            missing = tuple(
                pid for pid, monitor in items if monitor.get_order(key) is None
            )
            if missing:
                holders = tuple(
                    pid for pid, monitor in items if monitor.get_order(key) is not None
                )
                verdict.violations.append(
                    Violation(
                        KEYSET_DIVERGENCE,
                        f"key executed on p{list(holders)} but never on "
                        f"p{list(missing)}",
                        key=key,
                        pids=missing + holders,
                    )
                )

    def _check_write_orders(self, items, verdict: AuditVerdict) -> None:
        """Per-key total-order agreement of conflicting *writes* (reads
        commute; the monitor's read/write split mirrors the KeyDeps
        split).  The counterexample is the first diverging position."""
        pid_a, monitor_a = items[0]
        for pid_b, monitor_b in items[1:]:
            for key in monitor_a.keys():
                order_a = monitor_a.get_write_order(key)
                order_b = monitor_b.get_write_order(key)
                if order_a is None or order_b is None or order_a == order_b:
                    continue
                position, mine, theirs = first_divergence_index(order_a, order_b)
                verdict.violations.append(
                    Violation(
                        ORDER_DIVERGENCE,
                        f"write orders diverge at position {position}: "
                        f"p{pid_a} executed {mine}, p{pid_b} executed {theirs}",
                        key=key,
                        pids=(pid_a, pid_b),
                        entries=(position, mine, theirs),
                    )
                )

    def _check_multisets(self, items, verdict: AuditVerdict, commit_logs) -> None:
        """Executed-command multiset agreement per key.  A rifl executed
        at one replica but missing at another is classified
        committed-then-lost when the missing replica's own commit log
        proves it committed the command (it accepted the commit, then
        lost it) — else plain multiset divergence (which may also be an
        unsettled tail; the write-order check above is the sharp one)."""
        from collections import Counter

        pid_a, monitor_a = items[0]
        committed_rifls: Dict[int, set] = {}
        for pid, log in (commit_logs or {}).items():
            committed_rifls[pid] = {
                rifl for rifl, _value in log.values() if rifl is not None
            }
        for pid_b, monitor_b in items[1:]:
            for key in monitor_a.keys():
                full_a = Counter(monitor_a.get_order(key) or ())
                full_b = Counter(monitor_b.get_order(key) or ())
                if full_a == full_b:
                    continue
                only_a = full_a - full_b
                only_b = full_b - full_a
                for rifl in sorted(only_a):
                    missing_at = pid_b
                    if rifl in committed_rifls.get(missing_at, ()):
                        verdict.violations.append(
                            Violation(
                                COMMITTED_LOST,
                                f"{rifl} executed on p{pid_a} and committed "
                                f"on p{missing_at}, but never executed there",
                                key=key,
                                pids=(pid_a, missing_at),
                                entries=(rifl,),
                            )
                        )
                    else:
                        verdict.violations.append(
                            Violation(
                                MULTISET_DIVERGENCE,
                                f"{rifl} executed on p{pid_a} but not on "
                                f"p{missing_at}",
                                key=key,
                                pids=(pid_a, missing_at),
                                entries=(rifl,),
                            )
                        )
                for rifl in sorted(only_b):
                    verdict.violations.append(
                        Violation(
                            MULTISET_DIVERGENCE,
                            f"{rifl} executed on p{pid_b} but not on p{pid_a}",
                            key=key,
                            pids=(pid_b, pid_a),
                            entries=(rifl,),
                        )
                    )

    def _check_commit_logs(self, commit_logs, verdict: AuditVerdict) -> None:
        """Per-dot commit-value agreement: the same identifier (a dot for
        leaderless protocols, a slot for FPaxos) must commit the same
        (rifl, value) everywhere — Newt timestamp agreement, graph deps
        agreement, Caesar (clock, deps) agreement, FPaxos slot-order
        agreement, all as one check."""
        idents: Dict[Any, Dict[int, Tuple[Optional[Rifl], Any]]] = {}
        for pid, log in sorted(commit_logs.items()):
            for ident, record in log.items():
                idents.setdefault(ident, {})[pid] = record
        for ident, per_pid in sorted(idents.items(), key=lambda kv: str(kv[0])):
            if len(per_pid) < 2:
                continue
            records = sorted(per_pid.items())
            pid_a, record_a = records[0]
            for pid_b, record_b in records[1:]:
                if record_a != record_b:
                    verdict.violations.append(
                        Violation(
                            COMMIT_DIVERGENCE,
                            f"{ident} committed as {record_a} on p{pid_a} "
                            f"but {record_b} on p{pid_b}",
                            pids=(pid_a, pid_b),
                            entries=(ident, record_a, record_b),
                        )
                    )
                    break  # one witness per ident


def first_divergence_index(order_a, order_b) -> Tuple[int, Any, Any]:
    """First position where two sequences disagree; missing entries
    (one sequence shorter) report None on that side."""
    for index, (a, b) in enumerate(zip(order_a, order_b)):
        if a != b:
            return index, a, b
    shorter = min(len(order_a), len(order_b))
    return (
        shorter,
        order_a[shorter] if len(order_a) > shorter else None,
        order_b[shorter] if len(order_b) > shorter else None,
    )


# --- chained execution digests (the run layer's online instrument) ---


class DigestEntry(NamedTuple):
    """One executed write in a key's hash chain."""

    src: int
    seq: int
    digest: str


class ExecutionDigest:
    """Per-key hash chain over executed writes.

    ``record`` extends the chain with H(prev || rifl || op || value);
    position ``i`` of a chain therefore authenticates the whole write
    prefix up to and including write ``i``.  Two replicas agree on a
    key's first ``k`` writes iff their chains' entry ``k-1`` digests are
    equal, so a summary of (count, digest-at-count) lets any replica at
    least as far along verify a peer's entire prefix — the property the
    run layer's heartbeat piggyback rides.  Whole chains are kept (audit
    mode is opt-in and workload-bounded) so a mismatch resolves to the
    *first* diverging entry, not just "somewhere before count"."""

    def __init__(self) -> None:
        self._chains: Dict[Key, List[DigestEntry]] = {}

    def record(self, key: Key, rifl: Rifl, op_kind: str, value: Optional[str]) -> None:
        chain = self._chains.setdefault(key, [])
        prev = chain[-1].digest if chain else ""
        payload = f"{prev}|{key}|{rifl.source}.{rifl.sequence}|{op_kind}|{value}"
        digest = hashlib.sha256(payload.encode()).hexdigest()[:32]
        chain.append(DigestEntry(rifl.source, rifl.sequence, digest))

    def summary(self) -> Dict[Key, Tuple[int, str]]:
        """{key: (write count, digest at that count)} — what the
        heartbeat ships."""
        return {
            key: (len(chain), chain[-1].digest)
            for key, chain in self._chains.items()
            if chain
        }

    def entries(self, key: Key) -> List[DigestEntry]:
        return list(self._chains.get(key, ()))

    def mismatched_keys(
        self, peer_summary: Dict[Key, Tuple[int, str]]
    ) -> List[Key]:
        """Keys where WE can prove divergence: our chain reaches the
        peer's count and our digest at that position differs.  Keys where
        the peer is ahead are its responsibility (it runs the same check
        on our summary)."""
        out = []
        for key, (peer_count, peer_digest) in peer_summary.items():
            chain = self._chains.get(key)
            if chain is None or len(chain) < peer_count or peer_count == 0:
                continue
            if chain[peer_count - 1].digest != peer_digest:
                out.append(key)
        return sorted(out)

    @staticmethod
    def first_divergence(
        mine: Iterable[DigestEntry], theirs: Iterable[DigestEntry]
    ) -> Optional[Tuple[int, Optional[DigestEntry], Optional[DigestEntry]]]:
        """First position where two chains disagree (by digest), or None
        when one is a prefix of the other."""
        mine, theirs = list(mine), list(theirs)
        for index, (a, b) in enumerate(zip(mine, theirs)):
            if a.digest != b.digest:
                return index, a, b
        return None

    def merge_summary_into(self, out: Dict[Key, Tuple[int, str]]) -> None:
        """Fold this digest's summary into ``out`` (executor pools route
        disjoint key sets, so plain update is exact)."""
        out.update(self.summary())
