"""Planet: inter-region latency model used by the simulator and planner.

Reference: fantoch/src/planet/{mod,region,dat}.rs.  Latencies come from real
GCP (20 regions) / AWS (19 regions) ping measurements; we ship them
pre-parsed as ``fantoch_tpu/data/latency.json`` (floor of the avg ping,
intra-region latency 0 — matching fantoch/src/planet/dat.rs:33-75 and
``INTRA_REGION_LATENCY`` in fantoch/src/planet/mod.rs:19).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

INTRA_REGION_LATENCY = 0

_DATA_PATH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "data", "latency.json")


@dataclass(frozen=True, order=True)
class Region:
    """A named region (fantoch/src/planet/region.rs)."""

    name: str

    def __str__(self) -> str:
        return self.name


class Planet:
    """Latency oracle over a set of regions (fantoch/src/planet/mod.rs:21-140)."""

    def __init__(self, latencies: Dict[Region, Dict[Region, int]]):
        self._latencies = latencies
        # regions sorted by (distance, region) per source, matching the
        # reference's sort_unstable over (latency, region) tuples
        self._sorted: Dict[Region, List[Tuple[int, Region]]] = {
            src: sorted((lat, dst) for dst, lat in entries.items())
            for src, entries in latencies.items()
        }

    # --- constructors ---

    @staticmethod
    def new(dataset: str = "gcp") -> "Planet":
        """Load the GCP (default) or AWS ping dataset."""
        with open(_DATA_PATH) as f:
            raw = json.load(f)[dataset]
        latencies = {
            Region(src): {Region(dst): lat for dst, lat in entries.items()}
            for src, entries in raw.items()
        }
        return Planet(latencies)

    @staticmethod
    def from_latencies(latencies: Dict[Region, Dict[Region, int]]) -> "Planet":
        return Planet(latencies)

    @staticmethod
    def equidistant(planet_distance: int, region_number: int) -> Tuple[List[Region], "Planet"]:
        """Synthetic planet where all distinct regions are `planet_distance`
        apart (fantoch/src/planet/mod.rs:57-100)."""
        regions = [Region(f"r_{i}") for i in range(region_number)]
        latencies = {
            a: {b: (INTRA_REGION_LATENCY if a == b else planet_distance) for b in regions}
            for a in regions
        }
        return regions, Planet(latencies)

    # --- queries ---

    def regions(self) -> List[Region]:
        return list(self._latencies.keys())

    def ping_latency(self, from_: Region, to: Region) -> Optional[int]:
        entries = self._latencies.get(from_)
        if entries is None:
            return None
        return entries.get(to)

    def sorted_by_distance(self, from_: Region) -> Optional[List[Tuple[int, Region]]]:
        """Regions sorted by distance (ascending) from `from_`."""
        return self._sorted.get(from_)

    def latency_matrix(self, regions: List[Region]) -> np.ndarray:
        """Dense int64 RTT matrix for a region subset — device-friendly form
        consumed by the planner (fantoch_tpu/planner) and sim sweeps."""
        m = np.zeros((len(regions), len(regions)), dtype=np.int64)
        for i, a in enumerate(regions):
            for j, b in enumerate(regions):
                lat = self.ping_latency(a, b)
                assert lat is not None, f"missing latency {a} -> {b}"
                m[i, j] = lat
        return m
