from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import (
    AtomicIdGen,
    ClientId,
    Dot,
    IdGen,
    ProcessId,
    Rifl,
    RiflGen,
    ShardId,
    all_process_ids,
    process_ids,
)
from fantoch_tpu.core.kvs import KVOp, KVOpKind, KVOpResult, KVStore, Key, Value
from fantoch_tpu.core.metrics import Histogram, Metrics
from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.core.timing import RunTime, SimTime, SysTime
