"""Chaos fuzzing over the deterministic simulator: randomized fault
schedules, whole-system consistency auditing, and repro shrinking.

PRs 2/3/7/8 built every fault mechanism individually — link nemeses,
per-dot recovery, crash-restart + rejoin, overload shedding — but nothing
exercised their *cross-product*, and the chaos rows assert completion, not
safety.  The reference leans on stateright + quickcheck for that
assurance; our exhaustive checker (mc/checker.py) is capped at n=3/f=1 and
cannot reach WAL/overload/SlowProcess interleavings.  This module is the
scalable replacement: a seeded :class:`FaultPlanFuzzer` samples schedules
composing ALL existing nemeses (drop/dup/delay, partition+heal,
crash-forever, crash-restart, pause, slow-process, reorder jitter,
open-loop Poisson load) across protocol x n/f x conflict-rate configs,
drives the deterministic sim, and audits every run with the
:class:`~fantoch_tpu.core.audit.ConsistencyAuditor` — per-key write-order
agreement, exactly-once execution, committed-then-lost, commit-value
(timestamp/deps/slot) agreement.

Determinism contract: a :class:`FuzzCase` is a pure value; running it
twice yields byte-identical fault traces, monitors, and verdict digests
(``same seed => same plan => same trace => same verdict``), so every
finding is replayable from its JSON repro artifact
(``python -m fantoch_tpu.bin.fuzz repro <file>``).

When a case fails, :func:`shrink_case` minimizes it: greedy event removal
over the plan's components to a fixpoint (removing any remaining nemesis
makes the failure vanish), numeric halving of the workload, and time
bisection of the surviving fault windows — the quickcheck-shrinking idiom
ported to whole-system schedules.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from fantoch_tpu.core.config import Config
from fantoch_tpu.errors import (
    QuorumLostError,
    SimStalledError,
    StalledExecutionError,
)
from fantoch_tpu.sim.faults import FaultPlan

# verdicts
OK = "ok"
VIOLATION = "violation"
STALL = "stall"
INCOMPLETE = "incomplete"

REPRO_FORMAT = "fantoch-fuzz-repro-v1"

@dataclass(frozen=True)
class ProtocolSpec:
    """How the fuzzer exercises one protocol.  Every protocol composes
    EVERY nemesis class — crash-forever (per-dot recovery for the
    leaderless protocols incl. Caesar's (clock, preds) synod, leader
    failover for FPaxos), crash-restart (snapshot/restore + MSync /
    MSlotSync rejoin catch-up), and all link/process faults.  The former
    ``crash_ok``/``restart_ok`` escape hatches (Caesar had no recovery,
    FPaxos no slot catch-up) died with PR 12: a spec now only names the
    (n, f) pool the sampler draws from, and a skipped nemesis class would
    be a silent cap this matrix no longer has."""

    name: str
    # (n, f) pool the sampler draws from
    nf_pool: Tuple[Tuple[int, int], ...]


PROTOCOL_SPECS: Dict[str, ProtocolSpec] = {
    "epaxos": ProtocolSpec("epaxos", ((3, 1), (5, 1), (5, 2))),
    "atlas": ProtocolSpec("atlas", ((3, 1), (5, 1), (5, 2))),
    "newt": ProtocolSpec("newt", ((3, 1), (5, 1), (5, 2))),
    "fpaxos": ProtocolSpec("fpaxos", ((3, 1), (5, 1), (5, 2))),
    "caesar": ProtocolSpec("caesar", ((3, 1), (5, 1), (5, 2))),
}


# which device plane each protocol's executor drives (the accelerator
# fault nemesis only makes sense on plane-enabled configs): Newt's
# table executor, Caesar's predecessor executor, EPaxos/Atlas's graph
# executor; FPaxos's slot executor has no resident plane
DEVICE_PLANE_OF = {
    "newt": "table",
    "caesar": "pred",
    "epaxos": "graph",
    "atlas": "graph",
}

# config flags that turn the matching plane on
_DEVICE_PLANE_FLAGS = {
    "table": {"device_table_plane": True},
    "pred": {"device_pred_plane": True},
    "graph": {
        "device_graph_plane": True,
        "batched_graph_executor": True,
        "host_native_resolver": False,
    },
}


def _protocol_cls(name: str):
    from fantoch_tpu import protocol as protocols

    return {
        "epaxos": protocols.EPaxos,
        "atlas": protocols.Atlas,
        "newt": protocols.Newt,
        "fpaxos": protocols.FPaxos,
        "caesar": protocols.Caesar,
    }[name]


@dataclass(frozen=True)
class FuzzCase:
    """One replayable fuzz input: protocol + scale + workload + plan.
    A pure value — :func:`run_case` on the same case is byte-identical."""

    protocol: str
    n: int
    f: int
    plan: FaultPlan
    sim_seed: int = 0
    conflict_rate: int = 50
    keys_per_command: int = 2
    commands_per_client: int = 6
    clients_per_process: int = 2
    open_loop_rate_per_s: Optional[float] = None
    extra_sim_time_ms: int = 2000

    def to_dict(self) -> dict:
        out = dataclasses.asdict(self)
        out["plan"] = self.plan.to_dict()
        return out

    @staticmethod
    def from_dict(data: dict) -> "FuzzCase":
        data = dict(data)
        data["plan"] = FaultPlan.from_dict(data["plan"])
        return FuzzCase(**data)

    def digest(self) -> str:
        blob = json.dumps(self.to_dict(), sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()


@dataclass
class FuzzResult:
    """Verdict of one case run.  ``verdict_digest`` covers the verdict,
    the violations, and the committed/executed histories — the
    byte-identity anchor repro replay asserts against."""

    case: FuzzCase
    verdict: str
    violations: List[str] = field(default_factory=list)
    error: Optional[str] = None
    plan_digest: str = ""
    trace_digest: str = ""
    verdict_digest: str = ""
    # flight-recorder black boxes dumped by the failing run (populated
    # when run_case was given a flight_dir; excluded from the verdict
    # digest — timestamps inside make them run-local evidence, not part
    # of the byte-identity contract)
    flight: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return self.verdict == OK


class FaultPlanFuzzer:
    """Seeded sampler of fuzz cases.  ``case(index)`` is a pure function
    of (fuzzer seed, index): the per-case RNG is seeded with the string
    ``"{seed}:{index}"`` (string seeding is hash-randomization-free), so
    a sweep is reproducible from (seed, index range) alone."""

    # virtual-time horizon fault events are sampled inside
    HORIZON_MS = 1500

    def __init__(self, seed: int = 0):
        self.seed = seed

    def case(self, index: int, protocol: Optional[str] = None) -> FuzzCase:
        rng = random.Random(f"{self.seed}:{index}")
        name = protocol or rng.choice(sorted(PROTOCOL_SPECS))
        spec = PROTOCOL_SPECS[name]
        n, f = rng.choice(spec.nf_pool)
        conflict_rate = rng.choice((20, 50, 100))
        keys_per_command = 1 if conflict_rate == 100 else rng.choice((1, 2))
        plan = self._sample_plan(rng, n, f)
        plan = self._sample_device_faults(index, name, n, plan)
        open_loop = None
        if rng.random() < 0.25:
            # open-loop Poisson arrivals (the overload plane's sim
            # instrument): load keeps arriving regardless of completions
            open_loop = float(rng.choice((20, 50, 100)))
        return FuzzCase(
            protocol=name,
            n=n,
            f=f,
            plan=plan,
            sim_seed=rng.randrange(1 << 30),
            conflict_rate=conflict_rate,
            keys_per_command=keys_per_command,
            commands_per_client=rng.choice((4, 6, 8)),
            clients_per_process=2,
            open_loop_rate_per_s=open_loop,
        )

    def _sample_device_faults(
        self, index: int, protocol: str, n: int, plan: FaultPlan
    ) -> FaultPlan:
        """Maybe add accelerator faults (device_faults.py) against the
        protocol's device plane.  Drawn from a SEPARATE rng stream
        (``"{seed}:{index}:device"``) so arming this nemesis class left
        every pre-existing sampled case byte-identical."""
        plane = DEVICE_PLANE_OF.get(protocol)
        if plane is None:
            return plan
        rng = random.Random(f"{self.seed}:{index}:device")
        if rng.random() >= 0.25:
            return plan
        count = 1 if rng.random() < 0.8 else 2
        for _ in range(count):
            plan = plan.with_device_fault(
                process_id=rng.randrange(1, n + 1),
                plane=plane,
                kind=rng.choice(("hang", "raise", "corrupt")),
                at_dispatch=rng.randrange(1, 10),
                down_dispatches=rng.randrange(2, 6),
            )
        return plan

    def _sample_plan(self, rng: random.Random, n: int, f: int) -> FaultPlan:
        horizon = self.HORIZON_MS
        plan = FaultPlan(seed=rng.randrange(1 << 30), max_sim_time_ms=600_000)
        if rng.random() < 0.6:
            plan = plan.with_loss(round(rng.uniform(0.05, 0.3), 2))
        if rng.random() < 0.4:
            kwargs = {}
            if rng.random() < 0.5:
                kwargs["msg_types"] = rng.choice(
                    (("MCollect",), ("MCommit",), ("MCollect", "MCommit"))
                )
            if rng.random() < 0.5:
                # LATE duplicates: the copy lands long after the original
                # — past GC, where only the straggler guards keep it from
                # resurrecting pruned state (the PR 7 bug's trigger)
                kwargs["duplicate_delay_ms"] = rng.randrange(300, 900)
            plan = plan.with_link_fault(
                duplicate=round(rng.uniform(0.1, 0.3), 2), **kwargs
            )
        if rng.random() < 0.4:
            plan = plan.with_link_fault(extra_delay_ms=rng.randrange(10, 60))
        if rng.random() < 0.4:
            plan = plan.with_reorder(
                factor=round(rng.uniform(2.0, 8.0), 1),
                from_ms=rng.randrange(0, 200),
            )
        if rng.random() < 0.3:
            # symmetric cut between a minority group and the rest; always
            # heals (an unhealed partition is indistinguishable from > f
            # crashes — a liveness non-goal)
            cut = rng.sample(range(1, n + 1), max(1, n // 2 - 1))
            rest = [p for p in range(1, n + 1) if p not in cut]
            start = rng.randrange(100, 600)
            plan = plan.with_partition(
                [tuple(cut), tuple(rest)], start_ms=start,
                heal_ms=start + rng.randrange(100, 400),
            )
        if rng.random() < 0.5:
            # crash plans run with the sim failure detector on: FPaxos
            # must learn about a dead write-quorum member to reroute its
            # accept rounds (the run layer's heartbeat detector analog);
            # the leaderless protocols' hook is a no-op
            plan = dataclasses.replace(plan, detector_delay_ms=1000)
            # at most f crashed-at-once: every crash burns tolerance
            # budget while down; restarts return it, but overlapping
            # downtime windows must stay within f
            count = rng.randrange(1, f + 1)
            victims = rng.sample(range(1, n + 1), count)
            for victim in victims:
                at = rng.randrange(100, horizon // 2)
                restart = None
                if rng.random() < 0.5:
                    restart = at + rng.randrange(300, 800)
                plan = plan.with_crash(victim, at_ms=at, restart_at_ms=restart)
        if rng.random() < 0.3:
            victim = rng.randrange(1, n + 1)
            at = rng.randrange(100, horizon)
            plan = plan.with_pause(
                victim, at_ms=at, until_ms=at + rng.randrange(200, 600)
            )
        if rng.random() < 0.3:
            start = rng.randrange(0, horizon // 2)
            plan = plan.with_slow_process(
                rng.randrange(1, n + 1),
                slow_ms=rng.randrange(20, 80),
                from_ms=start,
                until_ms=start + rng.randrange(300, 900),
                jitter_ms=rng.randrange(0, 10),
            )
        return plan


# --- case execution ---


def _fuzz_config(case: FuzzCase) -> Config:
    """Audit-instrumented config for one case: execution-order monitors +
    commit logs always on; recovery wired whenever the plan crashes
    anyone (per-dot consensus for the leaderless protocols, leader
    failover for FPaxos)."""
    kwargs = dict(
        shard_count=1,
        executor_monitor_execution_order=True,
        audit_log_commits=True,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
    )
    if case.protocol == "newt":
        kwargs["newt_detached_send_interval_ms"] = 100
    if case.protocol == "fpaxos":
        kwargs["leader"] = 1
    if case.plan.crashes:
        kwargs["recovery_delay_ms"] = 1000
        kwargs["executor_monitor_pending_interval_ms"] = 500
        if case.protocol == "fpaxos":
            kwargs["fpaxos_leader_timeout_ms"] = 2000
    if case.plan.device_faults:
        # accelerator faults need the plane on plus the detection knobs:
        # a dispatch deadline (hangs surface as DeviceFailedError) and
        # always-on shadow checking (corruption surfaces on the faulted
        # dispatch, not whenever sampling happens to look)
        kwargs.update(_DEVICE_PLANE_FLAGS[DEVICE_PLANE_OF[case.protocol]])
        kwargs["device_dispatch_timeout_ms"] = 250.0
        kwargs["plane_shadow_rate"] = 1.0
    return Config(case.n, case.f, **kwargs)


def _fuzz_planet(n: int):
    """Uniform ~10ms planet: every process sits inside live fast quorums,
    so crashes always bite (the recovery-row topology of
    tests/test_faults.py, far=0)."""
    from fantoch_tpu.core.planet import Planet, Region

    regions = [Region(f"r{i}") for i in range(n)]
    latencies = {}
    for i, a in enumerate(regions):
        latencies[a] = {
            b: (0 if i == j else 10 + abs(i - j))
            for j, b in enumerate(regions)
        }
    return regions, Planet.from_latencies(latencies)


def run_case(case: FuzzCase, flight_dir: Optional[str] = None) -> FuzzResult:
    """Drive one case through the deterministic sim and audit the
    outcome.  Never raises for in-model failures: typed stalls become
    ``stall`` verdicts, safety violations (auditor findings OR internal
    protocol assertions) become ``violation``.

    ``flight_dir`` arms the flight recorder (observability/recorder.py):
    a stall or internal assertion dumps per-process black boxes there
    and the result's ``flight`` lists them — what the repro artifact
    attaches so every shrunk finding ships its own flight record."""
    from fantoch_tpu.client import ConflictRateKeyGen, Workload
    from fantoch_tpu.core.audit import ConsistencyAuditor
    from fantoch_tpu.sim import Runner

    protocol_cls = _protocol_cls(case.protocol)
    config = _fuzz_config(case)
    regions, planet = _fuzz_planet(case.n)
    workload = Workload(
        shard_count=1,
        key_gen=ConflictRateKeyGen(case.conflict_rate),
        keys_per_command=case.keys_per_command,
        commands_per_client=case.commands_per_client,
        payload_size=1,
    )
    runner = Runner(
        protocol_cls,
        planet,
        config,
        workload,
        case.clients_per_process,
        process_regions=list(regions),
        client_regions=list(regions),
        seed=case.sim_seed,
        fault_plan=case.plan,
        open_loop_rate_per_s=case.open_loop_rate_per_s,
        flight_dir=flight_dir,
    )
    result = FuzzResult(case, OK, plan_digest=_plan_digest(case.plan))
    try:
        _metrics, monitors, _latencies = runner.run(
            extra_sim_time_ms=case.extra_sim_time_ms
        )
    except (SimStalledError, StalledExecutionError, QuorumLostError) as exc:
        result.verdict = STALL
        result.error = f"{type(exc).__name__}: {exc}"
        result.flight = list(getattr(runner, "flight_dumps", []))
        _finalize_digests(result, runner, committed=None)
        return result
    except AssertionError as exc:
        # an internal safety assertion (e.g. the slot executor's
        # exactly-once guard, the vote table's collision check) IS a
        # consistency violation surfaced early
        result.verdict = VIOLATION
        result.violations = [f"internal-assertion: {exc}"]
        result.error = f"AssertionError: {exc}"
        result.flight = list(getattr(runner, "flight_dumps", []))
        _finalize_digests(result, runner, committed=None)
        return result

    crashed_forever = {
        crash.process_id
        for crash in case.plan.crashes
        if crash.restart_at_ms is None
    }
    # liveness: every client not attached to a crashed-forever replica
    # must have finished its whole workload
    unfinished = []
    for client_id, client in runner._simulation.clients():
        if client.targets() & crashed_forever:
            continue
        if client.issued_commands != case.commands_per_client:
            unfinished.append(client_id)
    if unfinished:
        result.verdict = INCOMPLETE
        result.error = f"clients {unfinished} did not finish"

    survivors = {
        pid: monitor
        for pid, monitor in monitors.items()
        if pid not in crashed_forever and monitor is not None
    }
    commit_logs = {
        pid: log
        for pid, (process, _e, _p) in runner._simulation.processes()
        if pid not in crashed_forever
        and (log := process.audit_commit_log()) is not None
    }
    verdict = ConsistencyAuditor().audit(survivors, commit_logs)
    if not verdict.ok:
        result.verdict = VIOLATION
        result.violations = [str(v) for v in verdict.violations]
    if not result.ok:
        # failures that do not raise (auditor violations, incomplete
        # clients) still ship their black box
        result.flight = runner.dump_flight(
            f"{result.verdict}: {(result.violations or [result.error])[0]}"
        )
    _finalize_digests(result, runner, committed=survivors)
    return result


def _plan_digest(plan: FaultPlan) -> str:
    blob = json.dumps(plan.to_dict(), sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def _finalize_digests(result: FuzzResult, runner, committed) -> None:
    result.trace_digest = (
        runner.nemesis.trace_digest() if runner.nemesis is not None else ""
    )
    digest = hashlib.sha256()
    digest.update(result.verdict.encode())
    digest.update(result.trace_digest.encode())
    for violation in result.violations:
        digest.update(violation.encode())
    if result.error:
        digest.update(result.error.encode())
    if committed:
        for pid, monitor in sorted(committed.items()):
            digest.update(f"p{pid}:{monitor!r}".encode())
    result.verdict_digest = digest.hexdigest()


# --- shrinking ---


def shrink_case(
    case: FuzzCase,
    still_fails: Optional[Callable[[FuzzCase], bool]] = None,
    max_runs: int = 150,
) -> Tuple[FuzzCase, int]:
    """Minimize a failing case: greedy removal of whole fault components
    to a fixpoint (after which removing ANY remaining nemesis makes the
    failure vanish — the minimality the self-test asserts), numeric
    halving of the workload, then time bisection of the surviving
    windows.  ``still_fails`` defaults to "run_case reports a violation";
    tests inject synthetic predicates to check the shrinker itself.
    Returns (shrunk case, verification runs spent)."""
    if still_fails is None:
        still_fails = lambda c: run_case(c).verdict == VIOLATION  # noqa: E731
    runs = 0

    def attempt(candidate: FuzzCase) -> bool:
        nonlocal runs
        if runs >= max_runs:
            return False
        runs += 1
        return still_fails(candidate)

    assert attempt(case), "shrink_case requires a failing case"

    component_fields = (
        "link_faults", "partitions", "crashes", "pauses", "slow_processes",
        "device_faults",
    )
    changed = True
    while changed and runs < max_runs:
        changed = False
        # pass 1: drop whole components
        for field_name in component_fields:
            index = 0
            while index < len(getattr(case.plan, field_name)):
                items = getattr(case.plan, field_name)
                candidate = dataclasses.replace(
                    case,
                    plan=dataclasses.replace(
                        case.plan,
                        **{field_name: items[:index] + items[index + 1:]},
                    ),
                )
                if attempt(candidate):
                    case = candidate
                    changed = True
                else:
                    index += 1
        if case.plan.reorder is not None:
            candidate = dataclasses.replace(
                case, plan=dataclasses.replace(case.plan, reorder=None)
            )
            if attempt(candidate):
                case = candidate
                changed = True
        if case.open_loop_rate_per_s is not None:
            candidate = dataclasses.replace(case, open_loop_rate_per_s=None)
            if attempt(candidate):
                case = candidate
                changed = True
        # pass 2: halve the workload toward 1
        for attr in ("commands_per_client", "clients_per_process", "keys_per_command"):
            while getattr(case, attr) > 1 and runs < max_runs:
                candidate = dataclasses.replace(
                    case, **{attr: getattr(case, attr) // 2}
                )
                if attempt(candidate):
                    case = candidate
                    changed = True
                else:
                    break
    # pass 3: time bisection over the surviving fault windows (bounded:
    # each window halves at most ~log2(horizon) times)
    case = _bisect_windows(case, attempt)
    return case, runs


def _bisect_windows(case: FuzzCase, attempt) -> FuzzCase:
    def try_replace(field_name, index, **changes):
        nonlocal case
        items = list(getattr(case.plan, field_name))
        items[index] = dataclasses.replace(items[index], **changes)
        candidate = dataclasses.replace(
            case,
            plan=dataclasses.replace(case.plan, **{field_name: tuple(items)}),
        )
        if attempt(candidate):
            case = candidate
            return True
        return False

    for index in range(len(case.plan.crashes)):
        while True:
            crash = case.plan.crashes[index]
            if crash.at_ms > 100 and try_replace(
                "crashes", index,
                at_ms=crash.at_ms // 2,
                restart_at_ms=(
                    None if crash.restart_at_ms is None
                    else crash.restart_at_ms - (crash.at_ms - crash.at_ms // 2)
                ),
            ):
                continue
            break
    for index in range(len(case.plan.pauses)):
        while True:
            pause = case.plan.pauses[index]
            span = pause.until_ms - pause.at_ms
            if span > 100 and try_replace(
                "pauses", index, until_ms=pause.at_ms + span // 2
            ):
                continue
            break
    for index in range(len(case.plan.partitions)):
        while True:
            part = case.plan.partitions[index]
            if part.heal_ms is None:
                break
            span = part.heal_ms - part.start_ms
            if span > 100 and try_replace(
                "partitions", index, heal_ms=part.start_ms + span // 2
            ):
                continue
            break
    return case


# --- repro artifacts ---


def repro_artifact(
    result: FuzzResult, shrink_runs: int = 0, issue: Optional[str] = None
) -> dict:
    """The JSON repro artifact for a finding.  Every protocol's findings
    fail the run the same way — the Caesar filed-as-issue special case
    (PR 9's carve-out for the then-unrecoverable wait-condition region)
    died with PR 12's Caesar recovery plane."""
    return {
        "format": REPRO_FORMAT,
        "case": result.case.to_dict(),
        "verdict": result.verdict,
        "violations": result.violations,
        "error": result.error,
        "plan_digest": result.plan_digest,
        "trace_digest": result.trace_digest,
        "verdict_digest": result.verdict_digest,
        "shrink_runs": shrink_runs,
        "issue": issue,
        # the shrunk finding's own black boxes (flight-recorder dumps,
        # observability/recorder.py) — readable by the same critpath
        # correlator as live traces
        "flight": result.flight,
    }


def write_repro(path: str, artifact: dict) -> None:
    with open(path, "w") as fh:
        json.dump(artifact, fh, sort_keys=True, indent=2)
        fh.write("\n")


def load_repro(path: str) -> dict:
    with open(path) as fh:
        artifact = json.load(fh)
    assert artifact.get("format") == REPRO_FORMAT, (
        f"not a fuzz repro artifact: {path}"
    )
    return artifact


def replay_repro(artifact: dict) -> Tuple[FuzzResult, bool]:
    """Re-run an artifact's case; returns (result, byte-identical) where
    byte-identical means the verdict digest matches the recorded one —
    same plan, same trace, same violations, same histories."""
    result = run_case(FuzzCase.from_dict(artifact["case"]))
    return result, result.verdict_digest == artifact["verdict_digest"]
