"""Single-threaded holder of processes (protocol, executor, pending) and
clients, with synchronous message forwarding.

Reference: fantoch/src/sim/simulation.rs:10-190.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.ids import ClientId, ProcessId
from fantoch_tpu.core.timing import SimTime
from fantoch_tpu.executor.aggregate import AggregatePending
from fantoch_tpu.executor.base import Executor
from fantoch_tpu.protocol.base import Protocol, ToSend


class Simulation:
    def __init__(self) -> None:
        self.time = SimTime()
        self._processes: Dict[ProcessId, Tuple[Protocol, Executor, AggregatePending]] = {}
        self._clients: Dict[ClientId, Client] = {}

    def register_process(self, process: Protocol, executor: Executor) -> None:
        process_id = process.id
        assert process_id not in self._processes, "process registered twice"
        pending = AggregatePending(process_id, process.shard_id)
        self._processes[process_id] = (process, executor, pending)

    def replace_process(
        self, process: Protocol, executor: Executor, pending: AggregatePending
    ) -> None:
        """Swap in a restarted process (restored from its durable image):
        the restart plane's re-registration seam (sim/runner.py)."""
        process_id = process.id
        assert process_id in self._processes, "restart requires a registered process"
        self._processes[process_id] = (process, executor, pending)

    def register_client(self, client: Client) -> None:
        assert client.id not in self._clients, "client registered twice"
        self._clients[client.id] = client

    def start_clients(self) -> List[Tuple[ClientId, ProcessId, Command]]:
        out = []
        for client in self._clients.values():
            nxt = client.next_cmd(self.time)
            assert nxt is not None, "clients should submit at least one command"
            target_shard, cmd = nxt
            out.append((client.id, client.shard_process(target_shard), cmd))
        return out

    def forward_to_processes(
        self, process_id: ProcessId, action: ToSend
    ) -> List[Tuple[ProcessId, object]]:
        """Deliver a ToSend action synchronously to all targets (self first);
        returns the newly produced actions of every touched process."""
        assert isinstance(action, ToSend), f"non supported action: {action}"
        process, _, _ = self._processes[process_id]
        shard_id = process.shard_id
        actions: List[Tuple[ProcessId, object]] = []
        if process_id in action.target:
            process.handle(process_id, shard_id, action.msg, self.time)
        # the first to_send entries are the ones from self
        actions.extend((process_id, a) for a in process.to_processes_iter())
        for to in action.target:
            if to == process_id:
                continue
            to_process, _, _ = self._processes[to]
            to_process.handle(process_id, shard_id, action.msg, self.time)
            actions.extend((to, a) for a in to_process.to_processes_iter())
        return actions

    def forward_to_client(self, cmd_result: CommandResult) -> Optional[Tuple[ProcessId, Command]]:
        """Deliver a command result; returns the client's next submission."""
        client = self._clients[cmd_result.rifl.source]
        client.handle([cmd_result], self.time)
        nxt = client.next_cmd(self.time)
        if nxt is None:
            return None
        target_shard, cmd = nxt
        return client.shard_process(target_shard), cmd

    def record_result(self, cmd_result: CommandResult) -> bool:
        """Open-loop result delivery: record the completion WITHOUT
        generating the next submission (arrivals are driven by the
        open-loop schedule, sim/runner.py); returns True once the client
        is done (workload generated and nothing in flight)."""
        client = self._clients[cmd_result.rifl.source]
        return client.handle([cmd_result], self.time)

    def get_process(self, process_id: ProcessId) -> Tuple[Protocol, Executor, AggregatePending]:
        return self._processes[process_id]

    def get_client(self, client_id: ClientId) -> Client:
        return self._clients[client_id]

    def processes(self):
        return self._processes.items()

    def clients(self):
        return self._clients.items()
