"""Discrete-event schedule: a min-heap of (time, action).

Reference: fantoch/src/sim/schedule.rs:6-60.  Popping advances the virtual
clock to the entry's schedule time.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Generic, List, Optional, Tuple, TypeVar

from fantoch_tpu.core.timing import SimTime

A = TypeVar("A")


class Schedule(Generic[A]):
    def __init__(self) -> None:
        self._heap: List[Tuple[int, int, A]] = []
        # tie-breaker keeps heap entries comparable without ordering actions
        # (insertion order within the same millisecond, like the reference's
        # arbitrary BinaryHeap tie order)
        self._counter = itertools.count()

    def schedule(self, time: SimTime, delay_ms: int, action: A) -> None:
        heapq.heappush(self._heap, (time.millis() + delay_ms, next(self._counter), action))

    def next_action(self, time: SimTime) -> Optional[A]:
        if not self._heap:
            return None
        schedule_time, _, action = heapq.heappop(self._heap)
        time.set_millis(schedule_time)
        return action

    def __len__(self) -> int:
        return len(self._heap)

    def actions(self):
        """Iterate pending actions (heap order, not delivery order) —
        the telemetry tick uses this to detect that it is the only thing
        left alive and stand down instead of spinning the loop forever."""
        for _time, _tie, action in self._heap:
            yield action
