"""Seeded device-fault nemesis: deterministic accelerator failures.

Every replica-level failure mode is already chaos-testable (sim/faults.py
crashes, partitions, pauses, link faults); this module makes the device
plane itself failable the same way.  A :class:`DeviceFault` describes one
accelerator failure against one process's plane — a dispatch that hangs,
an XLA runtime raise, or a silent bit-flip of a resident column — and a
:class:`DeviceFaultInjector` fires it deterministically.

Determinism is the whole design: faults are windowed in **dispatch
counts**, not wall or virtual time.  The plane's ``dispatches`` counter
advances identically on every same-seed run (it is driven purely by the
deterministic batch schedule), so "hang dispatches 12..15 of p2's pred
plane" replays bit-identically in the sim, under the fuzzer's shrinker,
and on a live rig — where a time-based window would race the scheduler.

The injector is *passive*: it never touches device state itself.  The
plane's guarded dispatch (executor/device_plane.py) asks
``on_dispatch(plane, n)`` before each fused call and applies the verdict
— short-circuiting a hung dispatch into its deadline, raising for a
``raise`` fault, or poisoning its own resident buffer for a ``corrupt``
fault (one high-bit flip of the first element of state array 0, so the
flip survives the kernel's monotone max/pass-through writes and the
shadow-check provably sees it).  ``rebuild_allowed`` vetoes the plane's
cutback re-upload while the fault window is still open — the device is
"still broken" — which is what makes time-to-cutback a measurable,
deterministic quantity.

Live drivers arm the same injector from the environment
(:func:`install_env_faults`, ``FANTOCH_DEVICE_FAULT=plane:kind:at[:down
[:pid]]``) so a real rig can rehearse failover without a sim.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple

PLANES = ("table", "pred", "graph")
KINDS = ("hang", "raise", "corrupt")

# corrupt flips this bit of resident state array 0, flat element 0:
# high enough that monotone kernels (frontier max, dep pass-through)
# keep the larger value instead of washing the flip out
DEFAULT_CORRUPT_BIT = 20

ENV_DEVICE_FAULT = "FANTOCH_DEVICE_FAULT"


@dataclass(frozen=True)
class DeviceFault:
    """One deterministic accelerator failure.

    ``process_id`` None targets every process's matching plane (the env
    install on a single-runtime driver); the sim plans always pin one.
    ``at_dispatch`` is the plane's ``dispatches`` counter value the
    fault first fires at; ``down_dispatches`` is how many subsequent
    dispatches the device stays broken for (hang/raise re-fire inside
    the window; rebuild is vetoed until the window closes).  ``corrupt``
    fires exactly once at ``at_dispatch`` — the bit-flip is the event —
    but the window still vetoes rebuild, modeling a device that keeps
    flipping bits until "repaired"."""

    plane: str
    kind: str
    at_dispatch: int
    down_dispatches: int = 4
    process_id: Optional[int] = None
    bit: int = DEFAULT_CORRUPT_BIT

    def __post_init__(self) -> None:
        if self.plane not in PLANES:
            raise ValueError(f"plane {self.plane!r} not in {PLANES}")
        if self.kind not in KINDS:
            raise ValueError(f"kind {self.kind!r} not in {KINDS}")
        if self.at_dispatch < 0:
            raise ValueError("at_dispatch must be >= 0")
        if self.down_dispatches < 1:
            raise ValueError("down_dispatches must be >= 1")

    def covers(self, dispatch: int) -> bool:
        return (
            self.at_dispatch
            <= dispatch
            < self.at_dispatch + self.down_dispatches
        )

    def to_dict(self) -> Dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, data: Dict) -> "DeviceFault":
        return cls(**data)


class DeviceFaultInjector:
    """The per-process injector a plane consults on every dispatch.

    ``record`` (optional) is called ``record(plane, kind, dispatch,
    detail)`` the moment a fault fires — the sim runner wires it into
    the nemesis trace so fault firings are part of the deterministic
    trace digest, and a live driver can wire it to its logger."""

    def __init__(
        self,
        faults: Sequence[DeviceFault],
        process_id: Optional[int] = None,
        record: Optional[Callable[[str, str, int, str], None]] = None,
    ):
        self.process_id = process_id
        self.record = record
        self._faults: List[DeviceFault] = [
            f
            for f in faults
            if f.process_id is None
            or process_id is None
            or f.process_id == process_id
        ]
        # corrupt faults fire exactly once; keyed by identity in the list
        self._corrupted: set = set()
        self.fired: int = 0

    def faults_for(self, plane: str) -> List[DeviceFault]:
        return [f for f in self._faults if f.plane == plane]

    def on_dispatch(self, plane: str, dispatch: int) -> Optional[DeviceFault]:
        """The fault this dispatch suffers, or None.  hang/raise fire on
        every dispatch inside their window; corrupt fires once at its
        window's first covered dispatch."""
        for index, fault in enumerate(self._faults):
            if fault.plane != plane or not fault.covers(dispatch):
                continue
            if fault.kind == "corrupt":
                if index in self._corrupted:
                    continue
                self._corrupted.add(index)
            self.fired += 1
            if self.record is not None:
                self.record(
                    plane,
                    fault.kind,
                    dispatch,
                    f"window [{fault.at_dispatch}, "
                    f"{fault.at_dispatch + fault.down_dispatches})",
                )
            return fault
        return None

    def rebuild_allowed(self, plane: str, dispatch: int) -> bool:
        """False while any fault window for this plane is still open:
        the device is still broken, cutback must wait."""
        return not any(
            f.plane == plane and f.covers(dispatch) for f in self._faults
        )


def faults_from_env(env: Optional[str] = None) -> Tuple[DeviceFault, ...]:
    """Parse ``FANTOCH_DEVICE_FAULT`` — one or more comma-separated
    ``plane:kind:at[:down[:pid]]`` specs — into :class:`DeviceFault`
    tuples (empty when unset), so live drivers rehearse the same
    deterministic failures the sim injects."""
    raw = os.environ.get(ENV_DEVICE_FAULT) if env is None else env
    if not raw:
        return ()
    faults = []
    for spec in raw.split(","):
        parts = spec.strip().split(":")
        if len(parts) < 3:
            raise ValueError(
                f"bad {ENV_DEVICE_FAULT} spec {spec!r}: want "
                "plane:kind:at[:down[:pid]]"
            )
        fault = DeviceFault(
            plane=parts[0], kind=parts[1], at_dispatch=int(parts[2])
        )
        if len(parts) > 3:
            fault = replace(fault, down_dispatches=int(parts[3]))
        if len(parts) > 4:
            fault = replace(fault, process_id=int(parts[4]))
        faults.append(fault)
    return tuple(faults)


def install_env_faults(
    planes: Sequence,
    process_id: Optional[int] = None,
    record: Optional[Callable[[str, str, int, str], None]] = None,
) -> Optional[DeviceFaultInjector]:
    """Attach one env-configured injector to every device plane of a
    live runtime (run/process_runner.py executor pools,
    run/device_runner.py drivers).  No-op (returns None) when
    ``FANTOCH_DEVICE_FAULT`` is unset or no plane exists."""
    faults = faults_from_env()
    if not faults:
        return None
    planes = [p for p in planes if p is not None]
    if not planes:
        return None
    injector = DeviceFaultInjector(faults, process_id, record=record)
    for plane in planes:
        plane.attach_injector(injector)
    return injector
