from fantoch_tpu.sim.faults import FaultPlan, Nemesis
from fantoch_tpu.sim.runner import Runner
from fantoch_tpu.sim.schedule import Schedule
from fantoch_tpu.sim.simulation import Simulation
