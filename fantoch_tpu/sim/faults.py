"""Deterministic fault-injection plane for the discrete-event simulator.

The protocols fantoch reproduces (EPaxos, Atlas, Newt, Caesar) claim
liveness and linearizability with up to ``f`` crashed replicas over a
lossy, partitionable network — yet the simulator delivers every message
exactly once over fixed planet latencies.  This module closes that gap in
the spirit of the reference's stateright exploration (``fantoch_mc``): a
:class:`FaultPlan` describes *what* goes wrong and *when* (virtual time),
a :class:`Nemesis` executes the plan with a dedicated seeded RNG, and the
runner (sim/runner.py) consults it at message send/delivery time.  Same
plan + same seed => byte-identical fault trace and committed-command
trace, so every chaos test is replayable.

Fault model (see README "Fault model" for the contract):

* **Link faults** — per-(src, dst) message drop, duplication, and extra
  delay inside a virtual-time window.  Drops default to
  ``retransmit=True``: the underlying channel is lossy but the connection
  layer retries with exponential backoff + jitter, exactly the TCP
  semantics the protocols assume (quasi-reliable links between correct
  processes).  The geometric retry sequence is collapsed into one
  deterministic delivery delay at send time, so retransmission costs no
  extra heap traffic.  ``retransmit=False`` models true message loss
  (protocol liveness is then *not* guaranteed — pair it with the bounded
  wait below).
* **Partitions** — symmetric cuts between process groups from
  ``start_ms`` until ``heal_ms``; crossing messages are deferred until
  just after heal (connection-retry semantics) or dropped forever when
  the partition never heals.
* **Crash** — a process stops at ``at_ms``: inbound messages are dropped,
  its periodic events stop, and clients attached to it are abandoned
  (the runner stops waiting for them).  With ``restart_at_ms`` set the
  crash is a crash-*restart* instead: the process returns to service
  from its durable image (snapshot/restore seam + MSync rejoin; see the
  :class:`Crash` docstring) and its clients are deferred, not abandoned.
* **Pause** — a transient freeze ``[at_ms, until_ms)``: inbound traffic
  and periodic events are deferred and replayed at resume, modelling a
  stop-the-world (GC pause, VM migration) rather than a crash.
* **SlowProcess** — a degraded consumer: deliveries into the process pick
  up a per-message handling delay inside a window, modelling an executor
  draining at a fraction of line rate (the overload plane's seeded
  slow-executor scenario) without being dead or paused.
* **Bounded wait** — ``max_sim_time_ms`` turns a stalled run (e.g. more
  than ``f`` members of an in-flight command's quorum crashed, so even the
  per-dot recovery consensus of ``protocol/recovery.py`` cannot gather an
  n-f promise quorum) into a typed
  :class:`~fantoch_tpu.errors.SimStalledError` instead of an infinite
  loop.  With ``Config.recovery_delay_ms`` set and at most ``f`` crashes,
  stalls *heal* instead: overdue dots go through prepare/promise recovery
  and commit (possibly as noops).
"""

from __future__ import annotations

import dataclasses
import hashlib
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

from fantoch_tpu.errors import SimStalledError  # noqa: F401  (re-export)
from fantoch_tpu.sim.device_faults import DeviceFault

# endpoint keys as used by sim/runner.py: ("process", pid) | ("client", cid)
EndpointKey = Tuple[str, int]


@dataclass(frozen=True)
class LinkFault:
    """Lossy-link behavior for messages src -> dst inside a time window.

    ``src``/``dst`` of None match any endpoint (including clients); an
    integer matches that *process* id.  ``msg_types`` optionally restricts
    the fault to payload class names (e.g. ``("MCommit",)``) — the
    targeted-drop primitive chaos tests use to strand dependencies.
    """

    src: Optional[int] = None
    dst: Optional[int] = None
    drop: float = 0.0
    duplicate: float = 0.0
    extra_delay_ms: int = 0
    # extra delay budget for the DUPLICATED copy only: a large value
    # models a late retransmit — the sender re-sent after losing the ack,
    # and the copy lands long after the original (possibly after the
    # commit went stable-everywhere and was GC'd: the straggler schedules
    # the GC-straggler guards exist for, and the one that reaches the
    # PR 7 commit-replay bug when those guards are off)
    duplicate_delay_ms: int = 0
    from_ms: int = 0
    until_ms: Optional[int] = None
    retransmit: bool = True
    msg_types: Optional[Tuple[str, ...]] = None

    def matches(self, now: int, src: Optional[int], dst: Optional[int], msg: Any) -> bool:
        if now < self.from_ms:
            return False
        if self.until_ms is not None and now >= self.until_ms:
            return False
        if self.src is not None and self.src != src:
            return False
        if self.dst is not None and self.dst != dst:
            return False
        if self.msg_types is not None and type(msg).__name__ not in self.msg_types:
            return False
        return True


@dataclass(frozen=True)
class Partition:
    """Symmetric cut between process groups during [start_ms, heal_ms).

    Processes in different groups cannot exchange messages while the
    partition is active; ``heal_ms=None`` never heals.  Processes in no
    group are unaffected (reachable from everyone).
    """

    groups: Tuple[Tuple[int, ...], ...]
    start_ms: int
    heal_ms: Optional[int] = None

    def active(self, now: int) -> bool:
        return now >= self.start_ms and (self.heal_ms is None or now < self.heal_ms)

    def separates(self, a: int, b: int) -> bool:
        ga = gb = None
        for index, group in enumerate(self.groups):
            if a in group:
                ga = index
            if b in group:
                gb = index
        return ga is not None and gb is not None and ga != gb


@dataclass(frozen=True)
class Crash:
    """A process failure at ``at_ms``, in one of two modes:

    * **Crash-forever** (``restart_at_ms=None``, the PR-2 behavior):
      the process stops for good — inbound messages are dropped, its
      periodic events stop, and clients attached to it are abandoned.
      Every such crash permanently burns one unit of the ``n - f``
      budget.
    * **Crash-restart** (``restart_at_ms`` set): the process loses all
      volatile state at ``at_ms`` and returns to service at
      ``restart_at_ms``.  The runner captures a *durable image* at the
      crash instant — the ``snapshot()`` seam on Protocol and Executor,
      modelling a synchronous WAL (``wal_sync=always``: every input
      applied before the crash was logged and is replayed; messages in
      flight at the crash are lost) — and at restart rebuilds the
      process from that image via ``restore()``, reschedules its
      periodic events, and runs the rejoin protocol
      (``Protocol.rejoin`` -> MSync catch-up from live peers, bounded by
      the executed-everywhere GC retention).  While the process is down,
      process-to-process messages to it are dropped (peers declared it
      dead); *client* messages are deferred past the restart with
      retransmit jitter (the client-reconnect-and-resubmit semantics of
      the run layer's reliable links), so its clients are NOT abandoned.
      A restarted process restores the full ``n - f`` tolerance budget —
      the chaos matrix asserts a *subsequent* crash of a different
      process still completes.
    """

    process_id: int
    at_ms: int
    restart_at_ms: Optional[int] = None

    def __post_init__(self) -> None:
        assert self.restart_at_ms is None or self.restart_at_ms > self.at_ms


@dataclass(frozen=True)
class Pause:
    process_id: int
    at_ms: int
    until_ms: int


@dataclass(frozen=True)
class SlowProcess:
    """Degraded-consumer nemesis (the overload plane's seeded scenario):
    while active, every message INTO ``process_id`` picks up ``slow_ms``
    of extra delivery delay (plus ``jitter_ms`` drawn from the nemesis
    RNG) — modelling an executor that drains its queues at a fraction of
    line rate (a wedged device, a GC-thrashing host) without being dead.
    Applied once per message at send time, so liveness is preserved and
    the slowdown is deterministic under the plan seed.  ``until_ms=None``
    never recovers."""

    process_id: int
    slow_ms: int
    from_ms: int = 0
    until_ms: Optional[int] = None
    jitter_ms: int = 0

    def active(self, now: int) -> bool:
        return now >= self.from_ms and (
            self.until_ms is None or now < self.until_ms
        )


@dataclass(frozen=True)
class ReorderJitter:
    """Seeded message-reorder nemesis: while active, every scheduled
    delivery's latency is multiplied by U(0, ``factor``) drawn from the
    nemesis RNG — the adversity the reference's sim applies globally
    (runner.rs:192-198, delivery delay x U(0, 10)), promoted from the
    runner's ad-hoc ``reorder_messages()`` knob to a first-class,
    windowable member of the fault plan so the chaos fuzzer can compose
    it with every other nemesis.  ``factor`` below 1 never happens for
    the whole window (a draw of 0 collapses latency to 0, maximally
    reordering against in-flight messages)."""

    factor: float = 10.0
    from_ms: int = 0
    until_ms: Optional[int] = None

    def active(self, now: int) -> bool:
        return now >= self.from_ms and (
            self.until_ms is None or now < self.until_ms
        )


@dataclass(frozen=True)
class FaultPlan:
    """Declarative, immutable fault schedule (builder-style constructors).

    The plan owns the determinism contract: every random decision the
    nemesis makes is drawn from ``random.Random(seed)`` in simulation
    order, so two runs of the same (plan, workload, sim seed) produce
    byte-identical traces.
    """

    seed: int = 0
    link_faults: Tuple[LinkFault, ...] = ()
    partitions: Tuple[Partition, ...] = ()
    crashes: Tuple[Crash, ...] = ()
    pauses: Tuple[Pause, ...] = ()
    slow_processes: Tuple[SlowProcess, ...] = ()
    # accelerator faults (sim/device_faults.py): deterministic dispatch
    # hangs / XLA raises / resident bit-flips against a process's device
    # plane, windowed in dispatch counts (not time) so same-seed runs
    # replay bit-identically.  Only meaningful on plane-enabled configs;
    # the runner attaches one injector per targeted process
    device_faults: Tuple["DeviceFault", ...] = ()
    reorder: Optional[ReorderJitter] = None
    # failure-detector model: when set, every crash-FOREVER is announced
    # to all live processes ``detector_delay_ms`` after the crash via
    # ``Protocol.on_peer_down`` — the sim analog of the run layer's
    # silence-based heartbeat detector (run/process_runner.py).  FPaxos
    # needs it to route accept rounds around a dead write-quorum member;
    # the leaderless protocols' hook is a no-op.  None (the default)
    # keeps the detector-less legacy model and byte-identical old traces
    detector_delay_ms: Optional[int] = None
    # base RTO for the collapsed retransmission sequence
    retransmit_base_ms: int = 25
    # bounded wait: virtual-time budget before a stalled run raises
    max_sim_time_ms: Optional[int] = None

    # --- builders ---

    def with_link_fault(self, **kwargs) -> "FaultPlan":
        return dataclasses.replace(
            self, link_faults=self.link_faults + (LinkFault(**kwargs),)
        )

    def with_loss(self, drop: float, **kwargs) -> "FaultPlan":
        """Uniform loss on every link (retransmitted by default)."""
        return self.with_link_fault(drop=drop, **kwargs)

    def with_crash(
        self, process_id: int, at_ms: int, restart_at_ms: Optional[int] = None
    ) -> "FaultPlan":
        """Crash-forever by default; pass ``restart_at_ms`` for a
        deterministic crash-and-restart (see :class:`Crash`)."""
        return dataclasses.replace(
            self, crashes=self.crashes + (Crash(process_id, at_ms, restart_at_ms),)
        )

    def with_pause(self, process_id: int, at_ms: int, until_ms: int) -> "FaultPlan":
        assert until_ms > at_ms
        return dataclasses.replace(
            self, pauses=self.pauses + (Pause(process_id, at_ms, until_ms),)
        )

    def with_slow_process(
        self,
        process_id: int,
        slow_ms: int,
        from_ms: int = 0,
        until_ms: Optional[int] = None,
        jitter_ms: int = 0,
    ) -> "FaultPlan":
        """Degraded-consumer window: the seeded slow-executor scenario
        the overload chaos rows are built on (see :class:`SlowProcess`)."""
        assert slow_ms > 0
        return dataclasses.replace(
            self,
            slow_processes=self.slow_processes
            + (SlowProcess(process_id, slow_ms, from_ms, until_ms, jitter_ms),),
        )

    def with_partition(
        self, groups, start_ms: int, heal_ms: Optional[int] = None
    ) -> "FaultPlan":
        part = Partition(tuple(tuple(g) for g in groups), start_ms, heal_ms)
        return dataclasses.replace(self, partitions=self.partitions + (part,))

    def with_reorder(
        self,
        factor: float = 10.0,
        from_ms: int = 0,
        until_ms: Optional[int] = None,
    ) -> "FaultPlan":
        """Seeded delivery-reorder jitter (see :class:`ReorderJitter`)."""
        assert factor > 0
        return dataclasses.replace(
            self, reorder=ReorderJitter(factor, from_ms, until_ms)
        )

    def with_device_fault(
        self,
        process_id: int,
        plane: str,
        kind: str,
        at_dispatch: int,
        down_dispatches: int = 4,
    ) -> "FaultPlan":
        """Deterministic accelerator failure against one process's
        device plane (see :class:`~fantoch_tpu.sim.device_faults
        .DeviceFault`): windowed in dispatch counts so the firing point
        is schedule-exact across same-seed runs."""
        fault = DeviceFault(
            plane=plane,
            kind=kind,
            at_dispatch=at_dispatch,
            down_dispatches=down_dispatches,
            process_id=process_id,
        )
        return dataclasses.replace(
            self, device_faults=self.device_faults + (fault,)
        )

    def crashed_ids(self) -> Tuple[int, ...]:
        return tuple(sorted({c.process_id for c in self.crashes}))

    # --- repro serialization (sim/fuzz.py artifacts) ---

    def to_dict(self) -> dict:
        """JSON-safe representation; round-trips via :meth:`from_dict`
        (the fuzzer's repro artifacts serialize plans this way)."""
        out = dataclasses.asdict(self)
        # asdict turns nested dataclasses into dicts but leaves tuples;
        # JSON round-trips tuples as lists, so from_dict re-tuples
        return out

    @staticmethod
    def from_dict(data: dict) -> "FaultPlan":
        return FaultPlan(
            seed=data.get("seed", 0),
            link_faults=tuple(
                LinkFault(**{**f, "msg_types": (
                    tuple(f["msg_types"]) if f.get("msg_types") else None
                )})
                for f in data.get("link_faults", ())
            ),
            partitions=tuple(
                Partition(
                    tuple(tuple(g) for g in p["groups"]),
                    p["start_ms"],
                    p.get("heal_ms"),
                )
                for p in data.get("partitions", ())
            ),
            crashes=tuple(
                Crash(**c) for c in data.get("crashes", ())
            ),
            pauses=tuple(Pause(**p) for p in data.get("pauses", ())),
            slow_processes=tuple(
                SlowProcess(**s) for s in data.get("slow_processes", ())
            ),
            device_faults=tuple(
                DeviceFault(**d) for d in data.get("device_faults", ())
            ),
            reorder=(
                ReorderJitter(**data["reorder"])
                if data.get("reorder") is not None
                else None
            ),
            detector_delay_ms=data.get("detector_delay_ms"),
            retransmit_base_ms=data.get("retransmit_base_ms", 25),
            max_sim_time_ms=data.get("max_sim_time_ms"),
        )


@dataclass
class NemesisMark:
    """Trace/bookkeeping marker the runner schedules at plan timestamps
    (crash / pause / resume / partition / heal) so state transitions are
    visible in the event trace and crash-time client accounting runs at
    the right virtual instant."""

    kind: str
    detail: str
    process_id: Optional[int] = None


# delivery verdicts for Nemesis.on_deliver
DELIVER = "deliver"
DROP = "drop"
DEFER = "defer"

_MAX_RETRANSMITS = 64


class Nemesis:
    """Executes a :class:`FaultPlan` over the simulator's message flow."""

    def __init__(self, plan: FaultPlan):
        import random

        self.plan = plan
        self.rng = random.Random(plan.seed)
        self.trace: List[Tuple[int, str, str]] = []
        # pid -> [(at_ms, restart_at_ms | None)] downtime windows; None
        # restart = crash-forever (a pid may crash again after a restart)
        self._crash_windows: dict = {}
        for crash in plan.crashes:
            self._crash_windows.setdefault(crash.process_id, []).append(
                (crash.at_ms, crash.restart_at_ms)
            )

    # --- trace ---

    def record(self, now: int, kind: str, detail: str) -> None:
        self.trace.append((now, kind, detail))

    def trace_lines(self) -> List[str]:
        return [f"t={t}ms {kind} {detail}" for t, kind, detail in self.trace]

    def trace_digest(self) -> str:
        digest = hashlib.sha256()
        for line in self.trace_lines():
            digest.update(line.encode())
            digest.update(b"\n")
        return digest.hexdigest()

    # --- fault state (pure functions of virtual time) ---

    def is_dead(self, process_id: int, now: int) -> bool:
        for at, restart in self._crash_windows.get(process_id, ()):
            if now >= at and (restart is None or now < restart):
                return True
        return False

    def restart_pending(self, process_id: int, now: int) -> Optional[int]:
        """The restart time of the downtime window covering ``now``, or
        None when the process is alive or crashed forever."""
        for at, restart in self._crash_windows.get(process_id, ()):
            if now >= at and restart is not None and now < restart:
                return restart
        return None

    def paused_until(self, process_id: int, now: int) -> Optional[int]:
        for pause in self.plan.pauses:
            if pause.process_id == process_id and pause.at_ms <= now < pause.until_ms:
                return pause.until_ms
        return None

    def marks(self) -> List[Tuple[int, NemesisMark]]:
        """(at_ms, mark) pairs the runner schedules up front."""
        out: List[Tuple[int, NemesisMark]] = []
        for crash in self.plan.crashes:
            out.append(
                (crash.at_ms, NemesisMark("crash", f"p{crash.process_id}", crash.process_id))
            )
            if crash.restart_at_ms is not None:
                out.append(
                    (
                        crash.restart_at_ms,
                        NemesisMark("restart", f"p{crash.process_id}", crash.process_id),
                    )
                )
        for pause in self.plan.pauses:
            out.append((pause.at_ms, NemesisMark("pause", f"p{pause.process_id}")))
            out.append((pause.until_ms, NemesisMark("resume", f"p{pause.process_id}")))
        for slow in self.plan.slow_processes:
            out.append(
                (
                    slow.from_ms,
                    NemesisMark("slow", f"p{slow.process_id} +{slow.slow_ms}ms"),
                )
            )
            if slow.until_ms is not None:
                out.append(
                    (slow.until_ms, NemesisMark("slow-end", f"p{slow.process_id}"))
                )
        for part in self.plan.partitions:
            groups = "|".join(",".join(map(str, g)) for g in part.groups)
            out.append((part.start_ms, NemesisMark("partition", groups)))
            if part.heal_ms is not None:
                out.append((part.heal_ms, NemesisMark("heal", groups)))
        reorder = self.plan.reorder
        if reorder is not None:
            out.append(
                (reorder.from_ms, NemesisMark("reorder", f"x{reorder.factor}"))
            )
            if reorder.until_ms is not None:
                out.append((reorder.until_ms, NemesisMark("reorder-end", "")))
        return out

    # --- send path ---

    @staticmethod
    def _pid(key: EndpointKey) -> Optional[int]:
        kind, id_ = key
        return id_ if kind == "process" else None

    def on_send(
        self,
        now: int,
        from_key: EndpointKey,
        to_key: EndpointKey,
        base_delay_ms: int,
        msg: Any,
    ) -> List[int]:
        """Delivery delays for one message: ``[]`` = dropped forever,
        one entry = normal (possibly retransmission-delayed) delivery,
        two entries = delivered + duplicated."""
        src, dst = self._pid(from_key), self._pid(to_key)
        reorder = self.plan.reorder
        if reorder is not None and reorder.active(now):
            # seeded reorder jitter: scale the base latency by U(0, factor)
            # BEFORE any fault branch, so deferred/retransmitted deliveries
            # compound on the reordered latency like real adversity would
            base_delay_ms = int(
                base_delay_ms * self.rng.uniform(0.0, reorder.factor)
            )
        label = f"{from_key[0]}{from_key[1]}->{to_key[0]}{to_key[1]} {type(msg).__name__}"
        if dst is not None and self.is_dead(dst, now):
            restart = self.restart_pending(dst, now)
            if restart is not None and from_key[0] == "client":
                # client traffic to a down-but-restarting process defers
                # past the restart (the client reconnects and resubmits —
                # the run layer's reliable-link semantics); peer traffic
                # still drops: peers declared the process dead and the
                # rejoin protocol, not the network, replays history
                delay = (
                    (restart - now)
                    + base_delay_ms
                    + self.rng.randint(1, self.plan.retransmit_base_ms)
                )
                self.record(now, "defer-restart", f"{label} +{delay}ms")
                return [delay]
            self.record(now, "drop-dead", label)
            return []
        delay = base_delay_ms
        if src is not None and dst is not None:
            for part in self.plan.partitions:
                if part.active(now) and part.separates(src, dst):
                    if part.heal_ms is None:
                        self.record(now, "drop-partition", label)
                        return []
                    # connection-level retry: delivered just after heal
                    delay = (
                        (part.heal_ms - now)
                        + base_delay_ms
                        + self.rng.randint(1, self.plan.retransmit_base_ms)
                    )
                    self.record(now, "defer-partition", f"{label} +{delay}ms")
                    break
        if dst is not None:
            # degraded-consumer nemesis: deliveries into a slowed process
            # pick up its handling delay (once, at send time — liveness
            # preserved, determinism via the plan RNG)
            for slow in self.plan.slow_processes:
                if slow.process_id == dst and slow.active(now):
                    extra = slow.slow_ms
                    if slow.jitter_ms:
                        extra += self.rng.randint(0, slow.jitter_ms)
                    delay += extra
                    break
        # EVERY matching fault composes (drop-with-retransmit delays,
        # extra delays, then duplication).  First-match-only semantics —
        # the original behavior — silently disabled a plan's targeted
        # dup/delay faults whenever a catch-all loss fault preceded them,
        # which is exactly how fuzzed schedules compose them
        matching = [
            f for f in self.plan.link_faults if f.matches(now, src, dst, msg)
        ]
        if not matching:
            return [delay]
        for fault in matching:
            if fault.drop and self.rng.random() < fault.drop:
                if not fault.retransmit:
                    self.record(now, "drop", label)
                    return []
                # collapse the geometric retry sequence (exponential
                # backoff, full jitter, capped) into one deterministic
                # extra delay
                rto = self.plan.retransmit_base_ms
                extra = 0
                attempts = 1
                while attempts < _MAX_RETRANSMITS:
                    extra += rto + self.rng.randint(0, rto)
                    rto = min(rto * 2, 8 * self.plan.retransmit_base_ms)
                    attempts += 1
                    if self.rng.random() >= fault.drop:
                        break
                delay += extra
                self.record(now, "retransmit", f"{label} x{attempts} +{extra}ms")
            if fault.extra_delay_ms:
                jitter = self.rng.randint(0, fault.extra_delay_ms)
                delay += jitter
                if jitter:
                    self.record(now, "delay", f"{label} +{jitter}ms")
        delays = [delay]
        # duplication only applies between processes: client channels carry
        # submit/result frames the client layer does not dedup (the run
        # layer's seq-numbered peer links are the real-world analog).  At
        # most one duplicate copy is produced (the first fault to roll it)
        for fault in matching:
            if (
                fault.duplicate
                and src is not None
                and dst is not None
                and self.rng.random() < fault.duplicate
            ):
                dup = delay + self.rng.randint(
                    1,
                    max(1, self.plan.retransmit_base_ms)
                    + fault.duplicate_delay_ms,
                )
                delays.append(dup)
                self.record(now, "duplicate", f"{label} +{dup}ms")
                break
        return delays

    # --- delivery path ---

    def on_deliver(self, now: int, process_id: int) -> Tuple[str, Optional[int]]:
        """Verdict for an action about to be handled by ``process_id``:
        (DELIVER, None) | (DROP, None) | (DEFER, resume_at_ms)."""
        if self.is_dead(process_id, now):
            return DROP, None
        until = self.paused_until(process_id, now)
        if until is not None:
            return DEFER, until
        return DELIVER, None
