"""Deterministic discrete-event simulator over Planet latencies.

Reference: fantoch/src/sim/runner.rs:33-700.  Processes live in regions;
message delivery takes half the ping latency between regions; periodic
events (protocol events + executor executed-notifications) are rescheduled
forever, so the loop ends when clients finish (plus optional extra time).
Optional adversity: symmetric distances, and random message reordering
(delivery delay multiplied by U(0, 10)) to stress executor ordering.
"""

from __future__ import annotations

import copy
import math
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ClientId, ProcessId, ShardId, process_ids
from fantoch_tpu.core.metrics import Histogram, Metrics
from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.errors import FaultToleranceError, SimStalledError
from fantoch_tpu.executor.monitor import ExecutionOrderMonitor
from fantoch_tpu.observability.tracer import NOOP_TRACER, Tracer, edge_dot
from fantoch_tpu.protocol.base import Protocol, ToForward, ToSend
from fantoch_tpu.run.ingest import (
    AdaptiveIngestBatcher,
    requested_ingest_deadline_ms,
    resolve_ingest_target,
)
from fantoch_tpu.sim.faults import DEFER, DELIVER, DROP, FaultPlan, Nemesis, NemesisMark
from fantoch_tpu.sim.schedule import Schedule
from fantoch_tpu.sim.simulation import Simulation
from fantoch_tpu.utils import closest_process_per_shard, sort_processes_by_distance


# schedule actions (runner.rs:20-26)
@dataclass
class SubmitToProc:
    process_id: ProcessId
    cmd: Command


@dataclass
class SendToProc:
    from_: ProcessId
    from_shard_id: ShardId
    to: ProcessId
    msg: Any
    # message-edge sequence for cross-process span stitching (set when
    # the message's dot is trace-sampled): the delivery emits the recv
    # half pairing with the send event stamped at schedule time.  A
    # nemesis-duplicated delivery shares the seq — the correlator keeps
    # the earliest receive, which is what unblocks the receiver
    edge_seq: Optional[int] = None


@dataclass
class SendToClient:
    client_id: ClientId
    cmd_result: CommandResult


@dataclass
class PeriodicProcessEvent:
    process_id: ProcessId
    event: Any
    delay_ms: int


@dataclass
class PeriodicExecutedNotification:
    process_id: ProcessId
    delay_ms: int


@dataclass
class OpenLoopArrival:
    """One open-loop client's next arrival tick: at handling time the
    client generates its next command (submitted regardless of
    completions) and the following arrival is scheduled at a seeded
    exponential gap — the virtual-time Poisson analog of the run layer's
    ``arrival_rate_per_s`` pacing (run/backpressure.OpenLoopPacer).  The
    overload plane's load instrument: closed-loop sim clients
    self-throttle and can never push the system past saturation."""

    client_id: ClientId


@dataclass
class IngestRelease:
    """Deadline tick of one process's adaptive ingest buffer
    (run/ingest.py wired into the sim): when it fires, the buffered
    submissions release toward the protocol unless a size-triggered
    release already emptied the buffer — then the tick re-polls and
    either stands down or rearms for the freshly opened window.  Riding
    the schedule keeps the batcher on virtual time: same seed, same
    release instants, byte-identical traces."""

    process_id: ProcessId


@dataclass
class TelemetryTick:
    """Virtual-time telemetry window boundary: every
    ``Config.telemetry_interval_ms`` (default 1 s) the runner emits one
    window line per process plus one for the client plane into the
    telemetry series (observability/timeseries.py).  Ticks only *read*
    state and their schedule is seed-independent, so same-seed runs emit
    byte-identical series — the determinism contract extended from
    traces to telemetry."""

    delay_ms: int


@dataclass
class PeerDownNotification:
    """Failure-detector tick (FaultPlan.detector_delay_ms): announce a
    crashed-forever process to every live protocol via
    ``Protocol.on_peer_down`` — the sim analog of the run layer's
    heartbeat detector (FPaxos reroutes accept rounds around dead
    write-quorum members on it; leaderless protocols no-op)."""

    dead: ProcessId


@dataclass
class PeriodicExecutorWatchdog:
    """Bounded-wait liveness check: under a fault plan, every executor's
    ``monitor_pending`` runs on this tick so a command stuck on
    dependencies from a dead replica surfaces a typed error instead of
    hanging the run (Config.executor_pending_fail_ms)."""

    process_id: ProcessId
    delay_ms: int


class Runner:
    def __init__(
        self,
        protocol_cls: type,
        planet: Planet,
        config: Config,
        workload: Workload,
        clients_per_process: int,
        process_regions: List[Region],
        client_regions: List[Region],
        seed: Optional[int] = None,
        fault_plan: Optional[FaultPlan] = None,
        trace_path: Optional[str] = None,
        open_loop_rate_per_s: Optional[float] = None,
        telemetry_path: Optional[str] = None,
        flight_dir: Optional[str] = None,
    ):
        assert len(process_regions) == config.n, "one region per process"
        assert config.gc_interval_ms is not None, "sim requires gc running"
        assert open_loop_rate_per_s is None or open_loop_rate_per_s > 0
        # open-loop mode: seeded Poisson arrivals at this per-client rate
        # drive submissions (closed loop submits on completion otherwise)
        self._open_loop_rate = open_loop_rate_per_s
        self._protocol_cls = protocol_cls
        self._planet = planet
        self._config = config
        self._simulation = Simulation()
        self._schedule: Schedule = Schedule()
        self._rng = random.Random(seed)
        # deterministic seed for the device-plane shadow sampler (the
        # fault plane hashes seed:plane:dispatch, so same-seed runs make
        # identical shadow decisions)
        self._seed = seed if seed is not None else 0
        self._make_distances_symmetric = False
        self._reorder_messages = False
        self._nemesis: Optional[Nemesis] = (
            Nemesis(fault_plan) if fault_plan is not None else None
        )
        # lifecycle tracing (fantoch_tpu/observability): virtual-clock
        # spans over the shared sim time source — same seed, same virtual
        # timestamps, byte-identical span log
        self._tracer = NOOP_TRACER
        if trace_path is not None and config.trace_sample_rate > 0:
            self._tracer = Tracer(
                self._simulation.time, trace_path, config.trace_sample_rate
            )
        # failure flight recorder (observability/recorder.py): one shared
        # ring for the whole sim (events carry their pid), dumped split
        # into flight_p<pid>.json files when a typed stall/violation
        # escapes the loop — the sim twin of the run layer's per-process
        # black boxes, correlated by the same critpath stitching
        self._flight = None
        self._flight_dir = flight_dir
        if flight_dir is not None or config.flight_recorder:
            from fantoch_tpu.observability.recorder import FlightRecorder

            self._flight_dir = flight_dir if flight_dir is not None else "."
            self._flight = FlightRecorder(
                self._simulation.time, inner=self._tracer, clock="virtual"
            )
            self._tracer = self._flight
        # per-sender message-edge sequences (cross-process stitching)
        self._edge_seqs: Dict[ProcessId, int] = {}
        # black boxes written by this runner (filled on typed failures,
        # or by an explicit dump_flight call)
        self.flight_dumps: List[str] = []
        # live telemetry (observability/timeseries.py): windowed series on
        # the virtual timeline — one window line per process + one for the
        # client plane per tick, byte-identical for same-seed runs
        self._telemetry = None
        self._telemetry_interval_ms = 0
        if telemetry_path is not None:
            from fantoch_tpu.observability.timeseries import (
                DEFAULT_WINDOW_MS,
                SeriesWriter,
            )

            self._telemetry_interval_ms = (
                config.telemetry_interval_ms or DEFAULT_WINDOW_MS
            )
            self._telemetry = SeriesWriter(
                telemetry_path,
                self._simulation.time,
                window_ms=self._telemetry_interval_ms,
            )
        # telemetry tallies: client submissions/replies (cluster level)
        # and per-process submit deliveries; the latency histogram is
        # maintained incrementally via the client observer seam (O(1)
        # per completion — never re-walked per window)
        self._client_submits = 0
        self._client_replies = 0
        self._submit_counts: Dict[ProcessId, int] = {}
        self._client_latency = Histogram()
        # adaptive ingest batching (run/ingest.py), opt-in: engages only
        # when a channel *requested* a deadline (Config field or env) and
        # it is positive — 0 and unset both mean the legacy
        # submit-immediately path, so the existing sim matrix is
        # bit-for-bit unchanged.  One batcher + buffer per process, all
        # on the virtual clock.
        deadline = requested_ingest_deadline_ms(None, config)
        self._ingest_deadline_ms = (
            deadline if deadline is not None and deadline > 0 else None
        )
        self._ingest_batchers: Dict[ProcessId, AdaptiveIngestBatcher] = {}
        self._ingest_buffers: Dict[ProcessId, List[Command]] = {}
        self._ingest_tick_armed: Dict[ProcessId, bool] = {}

        # a single shard in simulation
        shard_id = 0
        to_discover: List[Tuple[ProcessId, ShardId, Region]] = []
        processes: List[Tuple[Region, Protocol]] = []
        periodic_events: List[Tuple[ProcessId, Any, int]] = []
        periodic_executed: List[Tuple[ProcessId, int]] = []
        for region, process_id in zip(process_regions, process_ids(shard_id, config.n)):
            process, events = protocol_cls.new(process_id, shard_id, config)
            processes.append((region, process))
            periodic_events.extend((process_id, ev, delay) for ev, delay in events)
            interval = config.executor_executed_notification_interval_ms
            if interval is not None:
                periodic_executed.append((process_id, interval))
            to_discover.append((process_id, shard_id, region))

        self._process_to_region: Dict[ProcessId, Region] = {
            pid: region for pid, _, region in to_discover
        }
        # crash-restart plane: durable images captured at crash instants
        # (pid -> (protocol snapshot, executor snapshot, pending copy))
        # and the periodic-event actions dropped while a restarting
        # process was down (rescheduled at restart — each periodic stream
        # has exactly one live action, so a dropped one must come back)
        self._durable_images: Dict[ProcessId, Tuple[bytes, bytes, Any]] = {}
        self._stalled_periodics: Dict[ProcessId, List[Any]] = {}

        # register processes (discover with distance-sorted lists)
        for region, process in processes:
            sorted_processes = sort_processes_by_distance(region, planet, to_discover)
            connect_ok, _ = process.discover(sorted_processes)
            assert connect_ok
            executor = protocol_cls.Executor(process.id, process.shard_id, config)
            process.set_tracer(self._tracer)
            executor.set_tracer(self._tracer)
            self._arm_device_faults(executor, process.id)
            self._simulation.register_process(process, executor)

        # register clients
        client_id = 0
        self._client_to_region: Dict[ClientId, Region] = {}
        for region in client_regions:
            for _ in range(clients_per_process):
                client_id += 1
                client = Client(client_id, workload, rng=random.Random(self._rng.random()))
                closest = closest_process_per_shard(region, planet, to_discover)
                client.connect(closest)
                if self._telemetry is not None:
                    client.set_latency_observer(
                        lambda latency_us: self._client_latency.increment(
                            latency_us // 1000
                        )
                    )
                self._simulation.register_client(client)
                self._client_to_region[client_id] = region
        self._client_count = client_id
        # clients still owed results; crashes remove the ones attached to
        # dead processes so the loop does not wait for them forever
        self._active_clients = set(self._client_to_region)

        # schedule periodic events
        for process_id, event, delay in periodic_events:
            self._schedule.schedule(
                self._simulation.time, delay, PeriodicProcessEvent(process_id, event, delay)
            )
        for process_id, delay in periodic_executed:
            self._schedule.schedule(
                self._simulation.time, delay, PeriodicExecutedNotification(process_id, delay)
            )

        # telemetry windows ride the schedule like any periodic stream
        if self._telemetry is not None:
            self._schedule.schedule(
                self._simulation.time,
                self._telemetry_interval_ms,
                TelemetryTick(self._telemetry_interval_ms),
            )

        # fault plan: schedule state-transition marks at their virtual
        # timestamps, plus the executor bounded-wait watchdog
        if self._nemesis is not None:
            for at_ms, mark in self._nemesis.marks():
                self._schedule.schedule(self._simulation.time, at_ms, mark)
            watchdog = config.executor_monitor_pending_interval_ms
            if watchdog is not None:
                for pid in self._process_to_region:
                    self._schedule.schedule(
                        self._simulation.time,
                        watchdog,
                        PeriodicExecutorWatchdog(pid, watchdog),
                    )

    def _arm_device_faults(self, executor, process_id: ProcessId) -> None:
        """Wire the accelerator fault plane into this executor's device
        planes (no-op when it drives none): re-seed the shadow sampler
        from the sim seed, attach the FaultPlan's DeviceFault injector
        (per-process — every replica counts its own dispatches), and a
        failure listener that records each failover in the nemesis trace
        and dumps the flight ring (the black box for device failures)."""
        planes = executor.device_planes()
        if not planes:
            return
        for plane in planes:
            plane.configure_faults(
                self._config, seed=self._seed, process_id=process_id
            )
        device_faults = (
            self._nemesis.plan.device_faults
            if self._nemesis is not None
            else ()
        )
        if device_faults:
            from fantoch_tpu.sim.device_faults import DeviceFaultInjector

            def record(plane_name, kind, dispatch, detail, _pid=process_id):
                self._nemesis.record(
                    self._simulation.time.millis(),
                    f"device-{kind}",
                    f"p{_pid}:{plane_name}@{dispatch} {detail}",
                )

            injector = DeviceFaultInjector(
                device_faults, process_id=process_id, record=record
            )
            for plane in planes:
                plane.attach_injector(injector)

        def on_failure(plane, exc, _pid=process_id):
            if self._nemesis is not None:
                self._nemesis.record(
                    self._simulation.time.millis(),
                    "device-failover",
                    f"p{_pid}:{plane.plane_name} {type(exc).__name__}",
                )
            self.dump_flight(f"device-failover-p{_pid}-{plane.plane_name}")

        for plane in planes:
            plane.attach_failure_listener(on_failure)

    # --- adversity knobs (runner.rs:192-198) ---

    def make_distances_symmetric(self) -> None:
        self._make_distances_symmetric = True

    def reorder_messages(self) -> None:
        self._reorder_messages = True

    @property
    def tracer(self):
        return self._tracer

    def dump_flight(self, reason: str) -> List[str]:
        """Dump the flight ring on demand (no-op without a recorder):
        the post-run trigger for failures that do not raise — an
        auditor ``Violation`` classifies a *completed* run as unsafe,
        and its black box is this ring."""
        if self._flight is None:
            return []
        paths = self._flight.dump_all(self._flight_dir, reason)
        self.flight_dumps = paths
        return paths

    @property
    def nemesis(self) -> Optional[Nemesis]:
        return self._nemesis

    # --- main loop ---

    def run(
        self, extra_sim_time_ms: Optional[int] = None
    ) -> Tuple[
        Dict[ProcessId, Metrics],
        Dict[ProcessId, Optional[ExecutionOrderMonitor]],
        Dict[Region, Tuple[int, Histogram]],
    ]:
        """Run to completion; returns (process metrics, executor monitors,
        per-region (issued commands, latency histogram ms))."""
        tracer = self._tracer
        self.flight_dumps = []
        if self._open_loop_rate is not None:
            # open loop: arrivals drive submissions; the first arrival of
            # each client is itself an exponential gap from t=0
            for client_id in sorted(self._client_to_region):
                self._schedule_arrival(client_id)
        else:
            for client_id, process_id, cmd in self._simulation.start_clients():
                if tracer.enabled:
                    tracer.span("submit", cmd.rifl, cid=client_id)
                self._schedule_submit(("client", client_id), process_id, cmd)
        try:
            self._simulation_loop(extra_sim_time_ms)
        except (FaultToleranceError, AssertionError) as exc:
            # typed stalls (StalledExecutionError / SimStalledError /
            # divergence) and internal safety assertions are the flight
            # recorder's trigger: dump every live process's black box
            # before the error propagates (fuzz attaches these to repro
            # artifacts)
            if self._flight is not None:
                self.flight_dumps = self._flight.dump_all(
                    self._flight_dir, f"{type(exc).__name__}: {exc}"
                )
            raise
        finally:
            # flush+close so the span log is complete (and readable) even
            # when the loop raises a typed stall error
            tracer.close()
            if self._telemetry is not None:
                self._telemetry.close()
        return (
            {pid: p.metrics() for pid, (p, _, _) in self._simulation.processes()},
            {pid: e.monitor() for pid, (_, e, _) in self._simulation.processes()},
            self._clients_latencies(),
        )

    def _simulation_loop(self, extra_sim_time_ms: Optional[int]) -> None:
        extra_phase = False
        final_time = 0
        while True:
            action = self._schedule.next_action(self._simulation.time)
            if action is None:
                # only reachable under a fault plan: without one periodics
                # reschedule forever.  An empty schedule means the nemesis
                # dropped every remaining event (e.g. all processes
                # crashed) — clean exit if nobody is owed a result
                assert self._nemesis is not None, (
                    "there should be a next action (periodics always run)"
                )
                if not self._active_clients:
                    return
                now = self._simulation.time.millis()
                raise SimStalledError(now, now, self._active_clients)
            now = self._simulation.time.millis()
            if self._nemesis is not None:
                bound = self._nemesis.plan.max_sim_time_ms
                if bound is not None and now > bound and self._active_clients:
                    raise SimStalledError(now, bound, self._active_clients)
                action = self._apply_faults(action, now)
                if action is None:
                    continue
            if isinstance(action, TelemetryTick):
                self._handle_telemetry_tick(action)
            elif isinstance(action, PeriodicProcessEvent):
                self._handle_periodic_process_event(action)
            elif isinstance(action, PeriodicExecutedNotification):
                self._handle_periodic_executed_notification(action)
            elif isinstance(action, PeriodicExecutorWatchdog):
                self._handle_executor_watchdog(action)
            elif isinstance(action, SubmitToProc):
                self._handle_submit_to_proc(action.process_id, action.cmd)
            elif isinstance(action, SendToProc):
                if action.edge_seq is not None and self._tracer.enabled:
                    # recv half of the stitched hop (the send half was
                    # stamped at schedule time); duplicates share the
                    # seq and the correlator keeps the earliest
                    dot = edge_dot(action.msg)
                    if dot is not None:
                        self._tracer.edge(
                            "r", type(action.msg).__name__, action.from_,
                            action.to, action.edge_seq, dot=dot,
                        )
                self._handle_send_to_proc(action.from_, action.from_shard_id, action.to, action.msg)
            elif isinstance(action, OpenLoopArrival):
                self._handle_open_loop_arrival(action.client_id)
            elif isinstance(action, IngestRelease):
                self._handle_ingest_release(action.process_id)
            elif isinstance(action, PeerDownNotification):
                self._handle_peer_down_notification(action.dead)
            elif isinstance(action, SendToClient):
                if action.client_id not in self._active_clients:
                    continue  # abandoned (attached to a crashed process)
                self._client_replies += 1
                if self._tracer.enabled:
                    self._tracer.span(
                        "reply", action.cmd_result.rifl, cid=action.client_id
                    )
                if self._open_loop_rate is not None:
                    # open loop: record the completion only — arrivals,
                    # not completions, drive submissions
                    if self._simulation.record_result(action.cmd_result):
                        self._active_clients.discard(action.client_id)
                    continue
                submit = self._simulation.forward_to_client(action.cmd_result)
                if submit is not None:
                    process_id, cmd = submit
                    if self._tracer.enabled:
                        self._tracer.span(
                            "submit", cmd.rifl, cid=action.client_id
                        )
                    self._schedule_submit(("client", action.client_id), process_id, cmd)
                else:
                    self._active_clients.discard(action.client_id)
            else:
                raise AssertionError(f"unknown action {action}")
            if not extra_phase and not self._active_clients:
                if extra_sim_time_ms is None:
                    return
                extra_phase = True
                final_time = self._simulation.time.millis() + extra_sim_time_ms
            if extra_phase and self._simulation.time.millis() > final_time:
                return

    # --- fault plane (sim/faults.py) ---

    def _apply_faults(self, action: Any, now: int):
        """Nemesis delivery-time verdict for one popped action; returns the
        action to handle, or None when it was dropped, deferred, or was a
        nemesis bookkeeping mark."""
        if isinstance(action, NemesisMark):
            self._handle_nemesis_mark(action, now)
            return None
        if isinstance(action, PeerDownNotification):
            return action  # fans out to every live process itself
        process_id = None
        periodic = False
        if isinstance(
            action,
            (PeriodicProcessEvent, PeriodicExecutedNotification, PeriodicExecutorWatchdog),
        ):
            process_id, periodic = action.process_id, True
        elif isinstance(action, SubmitToProc):
            process_id = action.process_id
        elif isinstance(action, SendToProc):
            process_id = action.to
        if process_id is None:
            return action
        verdict, resume_ms = self._nemesis.on_deliver(now, process_id)
        if verdict == DELIVER:
            return action
        if verdict == DROP:
            restart_at = self._nemesis.restart_pending(process_id, now)
            if restart_at is not None:
                if isinstance(action, SubmitToProc):
                    # in-flight client submit at the crash: the client
                    # reconnects and resubmits after the restart (the
                    # reliable-link semantics; same policy as send-time
                    # defer in Nemesis.on_send)
                    delay = (restart_at - now) + self._nemesis.rng.randint(
                        1, self._nemesis.plan.retransmit_base_ms
                    )
                    self._nemesis.record(
                        now, "defer-restart", f"SubmitToProc->p{process_id} +{delay}ms"
                    )
                    self._schedule.schedule(self._simulation.time, delay, action)
                    return None
                if periodic:
                    # stash the stream's one live action; the restart
                    # handler reschedules it
                    self._stalled_periodics.setdefault(process_id, []).append(action)
                    return None
            # dead process: periodic events stop for good (never
            # rescheduled); in-flight messages evaporate
            if not periodic:
                self._nemesis.record(now, "drop-dead", f"{type(action).__name__}->p{process_id}")
            return None
        assert verdict == DEFER and resume_ms is not None
        self._schedule.schedule(self._simulation.time, resume_ms - now, action)
        return None

    def _handle_nemesis_mark(self, mark: NemesisMark, now: int) -> None:
        self._nemesis.record(now, mark.kind, mark.detail)
        if mark.kind == "crash" and mark.process_id is not None:
            if self._nemesis.restart_pending(mark.process_id, now) is not None:
                # crash-restart: capture the durable image at the crash
                # instant — the snapshot()/restore() seam, modelling a
                # synchronous WAL (wal_sync=always: every input applied
                # before the crash was logged; in-flight messages are
                # lost).  Clients stay active: their traffic defers past
                # the restart instead of evaporating.
                protocol, executor, pending = self._simulation.get_process(
                    mark.process_id
                )
                self._durable_images[mark.process_id] = (
                    protocol.snapshot(),
                    executor.snapshot(),
                    copy.deepcopy(pending),
                )
                self._nemesis.record(now, "durable-image", mark.detail)
                return
            # failure-detector model: announce the crash-forever to the
            # survivors after the detection delay (FaultPlan knob)
            if self._nemesis.plan.detector_delay_ms is not None:
                self._schedule.schedule(
                    self._simulation.time,
                    self._nemesis.plan.detector_delay_ms,
                    PeerDownNotification(mark.process_id),
                )
            # abandon clients attached to the dead process: their commands
            # can no longer complete, so the loop must not wait for them
            doomed = {
                client_id
                for client_id in self._active_clients
                if mark.process_id in self._simulation.get_client(client_id).targets()
            }
            if doomed:
                self._active_clients -= doomed
                self._nemesis.record(
                    now, "clients-abandoned", ",".join(map(str, sorted(doomed)))
                )
        elif mark.kind == "restart" and mark.process_id is not None:
            self._restart_process(mark.process_id)

    def _restart_process(self, process_id: ProcessId) -> None:
        """Bring a crashed process back: restore protocol + executor from
        the durable image, re-register, reschedule the periodic streams
        that died with it, then run the rejoin protocol (MSync catch-up
        from live peers past the restored commit horizon)."""
        proto_blob, exec_blob, pending = self._durable_images.pop(process_id)
        protocol = self._protocol_cls.restore(proto_blob)
        executor = self._protocol_cls.Executor.restore(exec_blob)
        protocol.set_tracer(self._tracer)
        executor.set_tracer(self._tracer)
        # device planes drop their injector/listener on pickling (live
        # handles): re-arm the fault plane exactly as at first boot
        self._arm_device_faults(executor, process_id)
        self._simulation.replace_process(protocol, executor, pending)
        for action in self._stalled_periodics.pop(process_id, []):
            self._schedule.schedule(self._simulation.time, action.delay_ms, action)
        protocol.rejoin(self._simulation.time)
        self._send_to_processes_and_executors(process_id)

    # --- handlers ---

    def _handle_peer_down_notification(self, dead: ProcessId) -> None:
        self._nemesis.record(
            self._simulation.time.millis(), "detect-down", f"p{dead}"
        )
        for pid in sorted(self._process_to_region):
            if pid == dead or self._nemesis.is_dead(
                pid, self._simulation.time.millis()
            ):
                continue
            process, _, _ = self._simulation.get_process(pid)
            process.on_peer_down(dead, self._simulation.time)
            self._send_to_processes_and_executors(pid)

    def _handle_periodic_process_event(self, ev: PeriodicProcessEvent) -> None:
        process, _, _ = self._simulation.get_process(ev.process_id)
        process.handle_event(ev.event, self._simulation.time)
        self._send_to_processes_and_executors(ev.process_id)
        self._schedule.schedule(self._simulation.time, ev.delay_ms, ev)

    def _handle_periodic_executed_notification(self, ev: PeriodicExecutedNotification) -> None:
        process, executor, _ = self._simulation.get_process(ev.process_id)
        executed = executor.executed(self._simulation.time)
        if executed is not None:
            process.handle_executed(executed, self._simulation.time)
            self._send_to_processes_and_executors(ev.process_id)
        self._schedule.schedule(self._simulation.time, ev.delay_ms, ev)

    def _handle_telemetry_tick(self, ev: TelemetryTick) -> None:
        """Emit one telemetry window per process + one for the client
        plane, then reschedule — unless the tick is the only pending
        stream left (everything else crashed/drained), in which case it
        stands down so the loop's empty-schedule logic (clean exit, or a
        typed SimStalledError when clients are still owed) keeps working
        exactly as it does without telemetry."""
        self._emit_telemetry()
        if any(
            not isinstance(action, TelemetryTick)
            for action in self._schedule.actions()
        ):
            self._schedule.schedule(self._simulation.time, ev.delay_ms, ev)

    def _emit_telemetry(self) -> None:
        """One window line per source, in deterministic (sorted) order:
        per-process protocol/executor counters + histograms, then the
        cluster-level client plane (submit/reply totals + a windowed
        client-latency histogram in ms)."""
        writer = self._telemetry
        for pid in sorted(self._process_to_region):
            process, executor, _ = self._simulation.get_process(pid)
            counters: Dict[str, float] = {
                "submitted": self._submit_counts.get(pid, 0),
            }
            hists: Dict[str, Histogram] = {}
            for prefix, metrics in (
                ("protocol", process.metrics()),
                ("executor", executor.metrics()),
            ):
                for kind, value in metrics.aggregated.items():
                    name = getattr(kind, "value", str(kind))
                    counters[f"{prefix}_{name}"] = value
                for kind, hist in metrics.collected.items():
                    name = getattr(kind, "value", str(kind))
                    hists[f"{prefix}_{name}"] = hist
            writer.emit(f"p{pid}", counters, hists=hists)
        latency = self._client_latency
        writer.emit(
            "clients",
            {
                "submitted": self._client_submits,
                "replied": self._client_replies,
            },
            hists={"latency_ms": latency},
        )

    def _handle_executor_watchdog(self, ev: PeriodicExecutorWatchdog) -> None:
        """Bounded-wait check: raises a typed StalledExecutionError (via
        Config.executor_pending_fail_ms) when a committed command has been
        waiting on never-committing dependencies past the bound.  Below the
        bound, the missing dots feed the protocol's recovery plane
        (Protocol.nudge_recovery): with Config.recovery_delay_ms set, a dot
        the executor is starving on is recovered by consensus — as a noop
        when its payload never reached any live process — instead of ever
        reaching the typed error."""
        process, executor, _ = self._simulation.get_process(ev.process_id)
        missing = executor.monitor_pending(self._simulation.time)
        if missing:
            process.nudge_recovery(missing, self._simulation.time)
        self._schedule.schedule(self._simulation.time, ev.delay_ms, ev)

    def _schedule_arrival(self, client_id: ClientId) -> None:
        """Schedule the client's next open-loop arrival at a seeded
        exponential gap (Poisson at ``open_loop_rate_per_s``); draws come
        from the runner RNG, so same-seed runs arrive identically.
        Gaps are rounded (not truncated) to the sim's ms granularity so
        the realized rate matches the configured one; the 1ms floor caps
        a single client at 1000 arrivals/s — spread higher offered rates
        over more clients."""
        gap_ms = max(1, round(self._rng.expovariate(self._open_loop_rate) * 1000))
        self._schedule.schedule(
            self._simulation.time, gap_ms, OpenLoopArrival(client_id)
        )

    def _handle_open_loop_arrival(self, client_id: ClientId) -> None:
        if client_id not in self._active_clients:
            return  # abandoned (attached to a crashed process)
        client = self._simulation.get_client(client_id)
        nxt = client.next_cmd(self._simulation.time)
        if nxt is None:
            # workload exhausted: no further arrivals; done once the
            # in-flight tail drains (record_result discards it then)
            if client.done:
                self._active_clients.discard(client_id)
            return
        target_shard, cmd = nxt
        if self._tracer.enabled:
            self._tracer.span("submit", cmd.rifl, cid=client_id)
        self._schedule_submit(
            ("client", client_id), client.shard_process(target_shard), cmd
        )
        self._schedule_arrival(client_id)

    def _handle_submit_to_proc(self, process_id: ProcessId, cmd: Command) -> None:
        self._submit_counts[process_id] = (
            self._submit_counts.get(process_id, 0) + 1
        )
        if self._tracer.enabled:
            # ingress edge: the client->coordinator hop's receive half
            # (the client's own `submit` span event is the send half)
            self._tracer.edge("r", "Submit", 0, process_id, 0, rifl=cmd.rifl)
        process, _, pending = self._simulation.get_process(process_id)
        pending.wait_for(cmd)
        if self._ingest_deadline_ms is None:
            process.submit(None, cmd, self._simulation.time)
            if self._tracer.enabled:
                # no batching gate: ingest coincides with the protocol's
                # payload stamp (a zero-width payload->ingest segment),
                # keeping the canonical stage chain complete
                self._tracer.span("ingest", cmd.rifl, pid=process_id)
            self._send_to_processes_and_executors(process_id)
            return
        # adaptive ingest plane: the coordinator owns the payload the
        # moment it arrives — stamped here so the hold until release is
        # the payload->ingest segment, attributed to batching instead of
        # hidden in a merged wait (this runner stamp precedes the
        # protocol's own payload stamp at submit, so it is the first
        # coordinator observation and wins canonical selection)
        if self._tracer.enabled:
            self._tracer.span("payload", cmd.rifl, pid=process_id)
        batcher = self._ingest_batchers.get(process_id)
        if batcher is None:
            batcher = AdaptiveIngestBatcher(
                self._ingest_deadline_ms,
                # a full protocol round has no device capacity bound here;
                # 1024 caps a release at the batched-executor sweet spot
                max_target=1024,
                fixed_target=resolve_ingest_target(None, self._config),
            )
            self._ingest_batchers[process_id] = batcher
        self._ingest_buffers.setdefault(process_id, []).append(cmd)
        batcher.note_arrivals(float(self._simulation.time.millis()), 1)
        self._ingest_poll(process_id)

    def _ingest_poll(self, process_id: ProcessId) -> None:
        """Release the process's ingest buffer if the batcher says so,
        else arm (at most) one deadline tick for the open window."""
        buf = self._ingest_buffers.get(process_id)
        if not buf:
            return
        batcher = self._ingest_batchers[process_id]
        release, wait_ms = batcher.poll(
            float(self._simulation.time.millis()), len(buf)
        )
        if release:
            self._ingest_release(process_id)
        elif wait_ms is not None and not self._ingest_tick_armed.get(process_id):
            self._ingest_tick_armed[process_id] = True
            self._schedule.schedule(
                self._simulation.time,
                # schedule granularity is whole virtual ms; never 0 so
                # the tick cannot livelock the loop at one instant
                max(1, math.ceil(wait_ms)),
                IngestRelease(process_id),
            )

    def _handle_ingest_release(self, process_id: ProcessId) -> None:
        self._ingest_tick_armed[process_id] = False
        if self._nemesis is not None and self._nemesis.is_dead(
            process_id, self._simulation.time.millis()
        ):
            # buffered-at-the-crash submissions evaporate like any other
            # in-flight input (the durable image excludes them); a
            # restart-deferred SubmitToProc re-buffers after the restart
            self._ingest_buffers[process_id] = []
            return
        # a size-triggered release may have emptied (and new arrivals
        # partially refilled) the buffer since this tick was armed:
        # re-poll so a freshly opened window keeps its full deadline
        self._ingest_poll(process_id)

    def _ingest_release(self, process_id: ProcessId) -> None:
        buf = self._ingest_buffers.get(process_id)
        if not buf:
            return
        self._ingest_buffers[process_id] = []
        self._ingest_batchers[process_id].note_release(
            float(self._simulation.time.millis()), len(buf)
        )
        process, _, _ = self._simulation.get_process(process_id)
        tracer = self._tracer
        for cmd in buf:
            if tracer.enabled:
                tracer.span("ingest", cmd.rifl, pid=process_id)
            process.submit(None, cmd, self._simulation.time)
        # one drain for the whole release: the executor sees the round's
        # infos as a batch, which is the throughput point of batching
        self._send_to_processes_and_executors(process_id)

    def _handle_send_to_proc(
        self, from_: ProcessId, from_shard_id: ShardId, to: ProcessId, msg: Any
    ) -> None:
        process, _, _ = self._simulation.get_process(to)
        process.handle(from_, from_shard_id, msg, self._simulation.time)
        self._send_to_processes_and_executors(to)

    def _send_to_processes_and_executors(self, process_id: ProcessId) -> None:
        """Drain a process's outputs: schedule network actions, feed execution
        infos to the executor, complete pending commands
        (runner.rs:396-435)."""
        process, executor, pending = self._simulation.get_process(process_id)
        shard_id = process.shard_id
        protocol_actions = list(process.to_processes_iter())
        ready: List[CommandResult] = []
        infos = list(process.to_executors_iter())
        if infos:
            # one protocol step's infos are handled as a batch so the
            # batched graph executor amortizes a device resolve over them
            executor.handle_batch(infos, self._simulation.time)
            for executor_result in executor.to_clients_iter():
                cmd_result = pending.add_executor_result(executor_result)
                if cmd_result is not None:
                    ready.append(cmd_result)
        self._schedule_protocol_actions(process_id, shard_id, protocol_actions)
        for cmd_result in ready:
            self._schedule_to_client(("process", process_id), cmd_result)

    def _schedule_protocol_actions(
        self, process_id: ProcessId, shard_id: ShardId, actions: List[Any]
    ) -> None:
        for action in actions:
            if isinstance(action, ToSend):
                # each target gets its own deep copy, matching the real
                # runner's serialize-per-connection semantics: receivers may
                # freely mutate payloads (Newt merges/strips Votes in place),
                # and aliasing one object across simulated processes would
                # silently leak state between them
                targets = sorted(action.target)
                copies = [action.msg] + [
                    copy.deepcopy(action.msg) for _ in range(len(targets) - 1)
                ]
                for to, msg in zip(targets, copies):
                    if to == process_id:
                        # message to self: deliver immediately
                        self._handle_send_to_proc(process_id, shard_id, process_id, msg)
                    else:
                        self._schedule_message(
                            ("process", process_id),
                            ("process", to),
                            SendToProc(process_id, shard_id, to, msg),
                        )
            elif isinstance(action, ToForward):
                # forwards are worker-to-worker: deliver immediately
                self._handle_send_to_proc(process_id, shard_id, process_id, action.msg)
            else:
                raise AssertionError(f"unknown action {action}")

    def _schedule_submit(self, from_region_key, process_id: ProcessId, cmd: Command) -> None:
        self._client_submits += 1
        self._schedule_message(
            from_region_key, ("process", process_id), SubmitToProc(process_id, cmd)
        )

    def _schedule_to_client(self, from_region_key, cmd_result: CommandResult) -> None:
        client_id = cmd_result.rifl.source
        if self._tracer.enabled and from_region_key[0] == "process":
            # reply edge: the coordinator->client hop's send half (the
            # client's `reply` span event is the receive half)
            self._tracer.edge(
                "s", "Reply", from_region_key[1], 0, 0, rifl=cmd_result.rifl
            )
        self._schedule_message(
            from_region_key, ("client", client_id), SendToClient(client_id, cmd_result)
        )

    def _schedule_message(self, from_key, to_key, action: Any) -> None:
        if isinstance(action, SendToProc) and self._tracer.enabled:
            # send half of a stitched peer hop, stamped at schedule time
            # (= the sender's "now"); the delivery emits the recv half
            dot = edge_dot(action.msg)
            if dot is not None and self._tracer.sample(dot):
                seq = self._edge_seqs.get(action.from_, 0) + 1
                self._edge_seqs[action.from_] = seq
                action.edge_seq = seq
                self._tracer.edge(
                    "s", type(action.msg).__name__, action.from_, action.to,
                    seq, dot=dot,
                )
        distance = self._distance(self._region_of(from_key), self._region_of(to_key))
        if self._reorder_messages:
            distance = int(distance * self._rng.uniform(0.0, 10.0))
        if self._nemesis is None:
            self._schedule.schedule(self._simulation.time, distance, action)
            return
        now = self._simulation.time.millis()
        msg = getattr(action, "msg", None) or getattr(action, "cmd", None) or action
        delays = self._nemesis.on_send(now, from_key, to_key, distance, msg)
        for index, delay in enumerate(delays):
            # a duplicated delivery gets its own deep copy: receivers may
            # mutate payloads in place (same reason ToSend fans out copies)
            copy_ = action if index == 0 else copy.deepcopy(action)
            self._schedule.schedule(self._simulation.time, delay, copy_)

    def _region_of(self, key) -> Region:
        kind, id_ = key
        if kind == "process":
            return self._process_to_region[id_]
        return self._client_to_region[id_]

    def _distance(self, from_: Region, to: Region) -> int:
        """Distance = half the ping latency (runner.rs:568-589)."""
        ping = self._planet.ping_latency(from_, to)
        assert ping is not None, "both regions should exist on the planet"
        if self._make_distances_symmetric:
            back = self._planet.ping_latency(to, from_)
            assert back is not None
            ping = (ping + back) // 2
        return ping // 2

    def _clients_latencies(self) -> Dict[Region, Tuple[int, Histogram]]:
        out: Dict[Region, Tuple[int, Histogram]] = {}
        for client_id, region in self._client_to_region.items():
            client = self._simulation.get_client(client_id)
            commands, histogram = out.setdefault(region, (0, Histogram()))
            commands += client.issued_commands
            for latency_micros in client.data().latency_data():
                histogram.increment(latency_micros // 1000)  # ms precision (WAN)
            out[region] = (commands, histogram)
        return out

    def serving_summary(self) -> Dict[str, object]:
        """Post-run serving view for the scenario observatory: completed
        commands, the cluster-wide serving span (first submit -> last
        completion, virtual ms — the goodput denominator, same
        reconstruction as run/harness.run_overload_phase), the pooled
        sorted µs latency list, and the device fault counters folded
        across every process's planes."""
        completed = 0
        latencies: List[int] = []
        first_start: Optional[float] = None
        last_end = 0
        for client_id in self._client_to_region:
            client = self._simulation.get_client(client_id)
            data = client.data()
            micros = list(data.latency_data())
            if not micros:
                continue
            completed += len(micros)
            latencies.extend(micros)
            start, end = data.span_millis()
            first_start = start if first_start is None else min(first_start, start)
            last_end = max(last_end, end)
        latencies.sort()
        device: Dict[str, float] = {
            "failovers": 0, "rebuilds": 0, "degraded_ms": 0.0
        }
        for _pid, (_process, executor, _pending) in self._simulation.processes():
            for plane in executor.device_planes():
                counters = plane.fault_counters()
                device["failovers"] += counters.get("failovers", 0)
                device["rebuilds"] += counters.get("rebuilds", 0)
                device["degraded_ms"] += counters.get("degraded_ms", 0.0)
        span_ms = (last_end - first_start) if first_start is not None else 0.0
        return {
            "completed": completed,
            "span_ms": span_ms,
            "latencies_us": latencies,
            "device": device,
        }
