"""Typed fault-tolerance errors shared by the sim, run, and executor layers.

The reference's failure handling is all-or-nothing (a lost connection or a
stuck command panics the process); growing toward the paper's actual claim
— liveness with up to ``f`` crashed replicas — needs failures that are
*classified*: a peer loss above quorum degrades, below quorum fails with a
typed error, and a command stuck past its bounded wait surfaces what it is
waiting on instead of hanging the driver.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional


class FaultToleranceError(Exception):
    """Base class for every typed fault-tolerance failure."""


class PeerLostError(FaultToleranceError):
    """A peer stayed unreachable past the reconnect budget."""

    def __init__(self, peer_id: int, attempts: int, last: Optional[BaseException]):
        self.peer_id = peer_id
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"peer p{peer_id} unreachable after {attempts} reconnect "
            f"attempts (last error: {last!r})"
        )


class QuorumLostError(FaultToleranceError):
    """Too many same-shard peers are gone for the protocol to stay live.

    Raised (surfaced through ``ProcessRuntime.failed``) when the number of
    live same-shard processes drops below ``n - f`` — the point past which
    no quorum can form and continuing would only hang clients.
    """

    def __init__(self, alive: int, needed: int, dead_peers: Iterable[int]):
        self.alive = alive
        self.needed = needed
        self.dead_peers = sorted(dead_peers)
        super().__init__(
            f"quorum lost: {alive} live processes < {needed} required "
            f"(dead peers: {self.dead_peers})"
        )


class StalledExecutionError(FaultToleranceError):
    """A committed command waited past the bounded-wait threshold on
    dependencies that never commit (e.g. dots owned by a crashed replica).

    ``missing`` maps each stuck dot to the dependency dots it is blocked
    on — the executor surfaces *what* it is waiting for instead of
    silently hanging the ordering engine.
    """

    def __init__(
        self,
        process_id: int,
        missing: Dict,
        waited_ms: int,
        recovery_delay_ms: Optional[int] = None,
    ):
        self.process_id = process_id
        self.missing = missing
        self.waited_ms = waited_ms
        if recovery_delay_ms is None:
            self.recovery_note = "recovery disabled (Config.recovery_delay_ms unset)"
        else:
            self.recovery_note = (
                f"recovery was attempted every {recovery_delay_ms}ms but "
                "could not commit these dots — likely no n-f promise "
                "quorum among the survivors"
            )
        detail = ", ".join(
            f"{dot} <- missing {sorted(map(str, deps))}"
            for dot, deps in sorted(missing.items(), key=lambda kv: str(kv[0]))
        )
        super().__init__(
            f"p{process_id}: execution stalled > {waited_ms}ms on "
            f"dependencies that never commit: {detail} [{self.recovery_note}]"
        )


class OverloadedError(FaultToleranceError):
    """A submission was shed by admission control at a client-facing edge
    (run/process_runner.py sessions, run/device_runner.py submit ring):
    the edge's queue depth crossed ``Config.admission_limit``, and
    executing the command late would only collapse latency for everyone.

    ``retry_after_ms`` is the server's hint (scaled by how far past the
    limit the queue sits); clients retry with capped exponential backoff
    floored by it (run/backpressure.Backoff), shedding the command
    themselves once its deadline budget expires.
    """

    def __init__(self, depth: int, limit: int, retry_after_ms: int):
        self.depth = depth
        self.limit = limit
        self.retry_after_ms = retry_after_ms
        super().__init__(
            f"overloaded: queue depth {depth} >= admission limit {limit}; "
            f"retry after {retry_after_ms}ms"
        )


class DeadlineExceededError(FaultToleranceError):
    """A command's per-command deadline budget expired before it completed
    — the client plane shed it (stopped retrying / stopped waiting)
    rather than let stale work consume capacity.  Carried as a client
    statistic in normal operation; raised only when a driver is asked to
    fail on sheds."""

    def __init__(self, rifl, waited_ms: float, deadline_ms: float):
        self.rifl = rifl
        self.waited_ms = waited_ms
        self.deadline_ms = deadline_ms
        super().__init__(
            f"deadline exceeded for {rifl}: waited {waited_ms:.0f}ms of a "
            f"{deadline_ms:.0f}ms budget"
        )


class DivergenceError(FaultToleranceError):
    """Two replicas executed different writes for the same key position —
    a safety violation, not a fault to tolerate.  Raised by the run
    layer's digest-exchange plane (``Config.execution_digests``:
    per-key chained digests piggybacked on the heartbeat path) naming the
    first diverging key + entry, and by audit tooling replaying histories.

    ``mine``/``theirs`` are the (source, sequence) command ids (rifls) the
    two replicas executed at ``position``; ``dot`` is the diverging
    command's proposal id when the protocol's audit commit log can resolve
    it (``Config.audit_log_commits``)."""

    def __init__(
        self,
        key: str,
        position: int,
        mine,
        theirs,
        process_id: int,
        peer_id: int,
        dot=None,
    ):
        self.key = key
        self.position = position
        self.mine = mine
        self.theirs = theirs
        self.process_id = process_id
        self.peer_id = peer_id
        self.dot = dot
        dot_note = f" (dot {dot})" if dot is not None else ""
        super().__init__(
            f"execution divergence on key {key!r} at write #{position}: "
            f"p{process_id} executed {mine}{dot_note} where p{peer_id} "
            f"executed {theirs}"
        )


class DeviceFailedError(FaultToleranceError):
    """A device plane's fused dispatch hung past the per-dispatch
    deadline (``Config.device_dispatch_timeout_ms``), or the XLA runtime
    raised out of it — the accelerator itself failed, not the protocol.

    The owning plane catches this internally: it transitions its health
    state machine (healthy -> suspect -> failed), serves the batch from
    the host twin, and rebuilds the resident state when the device
    recovers — so the executor API above it never observes the error,
    only the ``plane_failovers``/``degraded_ms`` counters do.

    ``kind`` names the detection channel: ``"hang"`` (an injected
    never-completing dispatch), ``"timeout"`` (a real dispatch that
    overran the deadline, measured at the blocking drain), or
    ``"raise"`` (the XLA runtime raised)."""

    def __init__(
        self,
        plane: str,
        process_id: Optional[int],
        kind: str,
        dispatch: int,
        timeout_ms: Optional[float] = None,
        cause: Optional[BaseException] = None,
    ):
        self.plane = plane
        self.process_id = process_id
        self.kind = kind
        self.dispatch = dispatch
        self.timeout_ms = timeout_ms
        self.cause = cause
        deadline = (
            f" (deadline {timeout_ms:.0f}ms)" if timeout_ms is not None else ""
        )
        cause_note = f": {cause!r}" if cause is not None else ""
        super().__init__(
            f"p{process_id}: {plane} plane dispatch #{dispatch} failed "
            f"[{kind}]{deadline}{cause_note}"
        )


class DeviceCorruptionError(FaultToleranceError):
    """A device plane's resident state silently diverged from the host
    twin — caught by the sampled shadow-check (``Config.plane_shadow_rate``
    replays a dispatch's inputs through the same kernel on host-owned
    state and compares bit-for-bit), named with the first diverging
    device row so the corruption is attributable like the digest
    auditor's first-diverging key.

    Like :class:`DeviceFailedError` this is caught inside the plane:
    the poisoned resident buffers are dropped, the batch is served from
    the (provably clean) twin, and a rebuild re-uploads the twin state.
    """

    def __init__(
        self,
        plane: str,
        process_id: Optional[int],
        dispatch: int,
        array_index: int,
        row: int,
        key=None,
    ):
        self.plane = plane
        self.process_id = process_id
        self.dispatch = dispatch
        self.array_index = array_index
        self.row = row
        self.key = key
        key_note = f" (key {key!r})" if key is not None else ""
        super().__init__(
            f"p{process_id}: {plane} plane resident state diverged from the "
            f"host twin at dispatch #{dispatch}: state array "
            f"{array_index}, first diverging row {row}{key_note}"
        )


class SimStalledError(FaultToleranceError):
    """The simulation passed its virtual-time bound with clients still
    waiting — the whole-system analog of :class:`StalledExecutionError`
    (e.g. every quorum of an in-flight command crashed)."""

    def __init__(self, time_ms: int, bound_ms: int, waiting_clients: Iterable[int]):
        self.time_ms = time_ms
        self.bound_ms = bound_ms
        self.waiting_clients = sorted(waiting_clients)
        super().__init__(
            f"simulation stalled: virtual time {time_ms}ms exceeded the "
            f"{bound_ms}ms bound with clients {self.waiting_clients} still "
            "waiting for results"
        )
